#!/usr/bin/env python
"""Headline benchmark: ERNIE/BERT-base pretrain samples/sec/chip.

BASELINE.json metric: "ERNIE-base pretrain samples/sec/chip". Runs the
flagship MLM+NSP train step (bf16 activations, fp32 master math, Adam,
fused attention) on the attached TPU chip.

Output contract: the driver parses the LAST stdout line as the headline
JSON. Ordering/robustness design (round-3 postmortem):
  * ONE bounded backend probe up front (watchdog thread). If the fabric
    hangs or the plugin fails, print the headline with an "error" field
    and exit inside ~2 minutes instead of burning the driver's timeout.
  * The ERNIE headline is MEASURED first so no secondary failure/hang can
    starve it; secondary lines are buffered and PRINTED first so the
    headline still lands last.
  * A global deadline thread force-prints whatever has been measured (and
    an error headline if the headline hasn't landed) then exits.
  * pallas_check line: flash-attention fwd+bwd Pallas-vs-XLA oracle run
    on the real chip — the only place the Mosaic path gets coverage.

vs_baseline: BASELINE.json carries no published numbers ("published": {}),
so the denominator is the reference's public era figure for this config:
PaddlePaddle fluid BERT-base seq128 pretraining throughput on one V100
(~50 samples/sec, PaddlePaddle/LARK benchmark tables) — i.e. vs_baseline
2.0 means 2x the reference's per-accelerator headline.
"""
import json
import os
import sys
import threading
import time

import numpy as np

REFERENCE_SAMPLES_PER_SEC = 50.0
# Secondary config (BASELINE metric string also names ResNet-50 images/sec):
# reference-era fluid ResNet-50 on one V100 ~ 360 images/sec.
REFERENCE_RESNET_IPS = 360.0

HEADLINE_METRIC = "ERNIE-base pretrain samples/sec/chip"

# bf16 peak FLOP/s per chip by device kind (MFU denominator)
_CHIP_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,   # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,   # trillium
}

_PROBE_TIMEOUT_S = float(os.environ.get("PADDLE_TPU_BENCH_PROBE_S", 90))
_DEADLINE_S = float(os.environ.get("PADDLE_TPU_BENCH_DEADLINE_S", 1500))
# Probe-retry keeps re-probing a wedged fabric, but must stop early enough
# that a late success still fits the headline measurement before deadline.
_MEASURE_RESERVE_S = float(
    os.environ.get("PADDLE_TPU_BENCH_MEASURE_RESERVE_S", 420))

# Buffered secondary lines + progress marker, shared with the watchdog.
_STATE = {"lines": [], "stage": "start", "headline": None,
          "t0": time.perf_counter()}


def _elapsed():
    return time.perf_counter() - _STATE["t0"]


def _error_headline(msg):
    return json.dumps({
        "metric": HEADLINE_METRIC, "value": 0.0,
        "unit": "samples/sec/chip", "vs_baseline": 0.0,
        "error": "%s (stage=%s)" % (msg, _STATE["stage"])})


def _flush_and_exit(code):
    """Print buffered secondaries, then the headline LAST, and hard-exit.
    os._exit: a wedged backend thread or a jax atexit hook touching the
    fabric must not be able to hang the interpreter shutdown."""
    for ln in _STATE["lines"]:
        print(ln)
    print(_STATE["headline"] if _STATE["headline"] is not None
          else _error_headline("no headline measured"))
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


def _arm_deadline():
    def fire():
        sys.stderr.write("bench deadline %.0fs exceeded at stage %s\n"
                         % (_DEADLINE_S, _STATE["stage"]))
        _flush_and_exit(3)
    t = threading.Timer(_DEADLINE_S, fire)
    t.daemon = True
    t.start()
    return t


def _probe_backend_subprocess(timeout):
    """ONE bounded backend-discovery attempt in a FRESH subprocess.

    A hung in-process probe thread wedges this interpreter's jax for good
    (the plugin holds its init lock forever), so retrying in-process after
    a hang can never succeed.  A subprocess probe leaves THIS process's
    jax un-imported until a probe reports the fabric healthy.  Returns
    (platforms, error, transient, timeline) — ``timeline`` is the
    per-phase attach triage record (see _attach_timeline)."""
    import subprocess
    # The axon sitecustomize forces jax_platforms at import, overriding the
    # JAX_PLATFORMS env var — apply the env var via config.update so an
    # explicit JAX_PLATFORMS=cpu (tests) actually probes CPU.
    # Each phase is stamped (flushed — a hang must not trap the stamps
    # in a block buffer) so a TimeoutExpired's partial stdout still
    # shows WHICH phase hung: the r3-r5 rounds said only "fabric hang",
    # never whether the plugin import or the jax.devices() device
    # enumeration was the wedge.
    code = ("import os, time, json;"
            "st=lambda p: print('PHASE:'+json.dumps"
            "({'phase': p, 't': time.time()}), flush=True);"
            "st('spawned');"
            "import jax;"
            "st('backend_import');"
            "p=os.environ.get('JAX_PLATFORMS');"
            "p and jax.config.update('jax_platforms', p);"
            "d=jax.devices();"
            "st('devices');"
            "print('PLATFORMS:'+json.dumps("
            "sorted({x.platform for x in d})))")
    t_spawn = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], text=True, timeout=timeout,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    except subprocess.TimeoutExpired as e:
        # a hang can be a transient fabric wedge — worth retrying; the
        # partial stdout carries every phase stamp that DID land
        timeline = _attach_timeline(t_spawn, e.stdout or "",
                                    timeout, hung=True)
        return None, "backend init exceeded %.0fs (fabric hang)" % timeout, \
            True, timeline
    except Exception as e:  # pragma: no cover
        return None, "probe subprocess failed: %r" % (e,), False, None
    timeline = _attach_timeline(t_spawn, proc.stdout, timeout,
                                hung=False)
    for ln in proc.stdout.splitlines():
        if ln.startswith("PLATFORMS:"):
            return json.loads(ln[len("PLATFORMS:"):]), None, False, \
                timeline
    # an instant nonzero exit (import error, broken plugin) is
    # deterministic — retrying until the deadline would only delay the
    # error headline by ~15 minutes
    return None, ("backend init failed rc=%d: %s"
                  % (proc.returncode, proc.stdout.strip()[-300:])), \
        False, timeline


# the probe's phase order — _attach_timeline names the first missing
# one as the hang site
_PROBE_PHASES = ("spawned", "backend_import", "devices")


def _attach_timeline(t_spawn, stdout, timeout_s, hung):
    """The attach triage record the headline carries next to
    attach_verdict: per-phase seconds since the probe subprocess was
    spawned (subprocess spawn -> python up -> jax/plugin import ->
    jax.devices() return), plus which phase a hang died inside. The
    next fabric-hang round then shows WHETHER the wedge is plugin
    import or device enumeration — the attribution ROADMAP's
    cross-cutting blocker has been missing."""
    stamps = {}
    if isinstance(stdout, bytes):
        # TimeoutExpired carries the partial capture as bytes on some
        # interpreter versions even under text=True
        stdout = stdout.decode("utf-8", "replace")
    for ln in (stdout or "").splitlines():
        if ln.startswith("PHASE:"):
            try:
                d = json.loads(ln[len("PHASE:"):])
                stamps[d["phase"]] = round(float(d["t"]) - t_spawn, 3)
            except (ValueError, KeyError, TypeError):
                continue
    missing = [p for p in _PROBE_PHASES if p not in stamps]
    timeline = {"phases": {p: stamps[p] for p in _PROBE_PHASES
                           if p in stamps},
                "probe_timeout_s": timeout_s}
    if hung:
        # the hang lives between the last stamped phase and the first
        # missing one: name the missing one (what never returned)
        timeline["hung_phase"] = missing[0] if missing else "report"
    return timeline


def _probe_backend(timeout=_PROBE_TIMEOUT_S):
    """Backend discovery with RETRY: keep re-probing (subprocess-isolated,
    backoff) until a probe succeeds or the global deadline nears.  The
    r4 postmortem: the fabric demonstrably wedges AND recovers within a
    round — a single 90s probe shipping a zero at T+90s forfeits the
    whole measurement window.  Budget: leave _MEASURE_RESERVE_S of the
    global deadline for the actual measurement once the fabric answers.

    Returns ``(platforms, err, verdict, timeline)`` where verdict
    classifies the attach for the headline JSON: ``"ok"``, ``"hang"``
    (every bounded probe timed out — the r3–r5 fabric symptom, the chip
    MAY be healthy next round) or ``"error"`` (deterministic init
    failure — plugin or environment, retrying won't help); ``timeline``
    is the LAST probe attempt's per-phase triage record (plus the
    attempt count), stamped into the headline next to attach_verdict."""
    attempt = 0
    while True:
        attempt += 1
        _STATE["stage"] = "backend-probe-%d" % attempt
        platforms, err, transient, timeline = \
            _probe_backend_subprocess(timeout)
        if timeline is not None:
            timeline["attempt"] = attempt
        if err is None:
            sys.stderr.write("backend probe %d: ok\n" % attempt)
            return platforms, None, "ok", timeline
        remaining = _DEADLINE_S - _elapsed()
        sys.stderr.write("backend probe %d failed (%s); %.0fs to deadline\n"
                         % (attempt, err, remaining))
        if not transient:
            return None, err, "error", timeline
        if remaining < _MEASURE_RESERVE_S + timeout:
            return None, "%s after %d probe attempts" % (err, attempt), \
                "hang", timeline
        time.sleep(min(30.0 * attempt, 120.0,
                       max(remaining - _MEASURE_RESERVE_S - timeout, 0)))


def _on_tpu():
    import jax
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def _micro_enabled():
    """The CPU microbench fallback is ALWAYS on — rounds 3–5 shipped
    zero perf signal because the fallback was opt-in and the driver
    didn't opt in.  ``--micro`` / PADDLE_TPU_BENCH_MICRO=1 are still
    accepted (existing CI command lines), and
    PADDLE_TPU_BENCH_MICRO=0 is the explicit opt-OUT for a driver
    that genuinely wants attach-or-nothing."""
    return os.environ.get("PADDLE_TPU_BENCH_MICRO") != "0"


def _run_micro_fallback(timeout=420):
    """Run bench_micro.py in a FRESH subprocess pinned to CPU (this
    process's jax may be wedged or deliberately un-imported after a
    probe failure — the same isolation rule as the probe itself).
    Returns its JSON report line, or None."""
    import subprocess
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench_micro.py")
    try:
        proc = subprocess.run([sys.executable, script], text=True,
                              timeout=timeout, stdout=subprocess.PIPE,
                              stderr=subprocess.DEVNULL, env=env)
    except Exception as e:
        sys.stderr.write("micro fallback failed: %r\n" % (e,))
        return None
    for ln in reversed(proc.stdout.splitlines()):
        if ln.startswith("{"):
            return ln
    sys.stderr.write("micro fallback produced no JSON (rc=%d)\n"
                     % proc.returncode)
    return None


def bert_train_flops(cfg, batch, seq, preds):
    """Analytic per-step training FLOPs of the MLM+NSP model (matmul terms;
    fwd + ~2x for backward — the standard MFU accounting)."""
    d, L, ff = cfg.hidden_size, cfg.num_layers, cfg.ff_size
    tokens = batch * seq
    proj = 8 * tokens * d * d           # Q,K,V,O projections
    attn = 4 * batch * seq * seq * d    # scores + context matmuls
    ffn = 4 * tokens * d * ff           # two FFN matmuls
    fwd = L * (proj + attn + ffn)
    fwd += 2 * batch * preds * d * cfg.vocab_size   # MLM vocab decode
    fwd += 2 * batch * preds * d * d                # MLM transform
    return 3 * fwd


def gpt_train_flops(cfg, batch, seq):
    """Analytic per-step training FLOPs of the causal LM (matmul terms;
    causal attention counts the lower triangle only — half the (T,T)
    matrix; same 3x fwd+bwd convention as bert_train_flops)."""
    d, L, ff = cfg.hidden_size, cfg.num_layers, cfg.ff_size
    tokens = batch * seq
    proj = 8 * tokens * d * d
    attn = 4 * batch * seq * seq * d // 2
    ffn = 4 * tokens * d * ff
    fwd = L * (proj + attn + ffn) + 2 * tokens * d * cfg.vocab_size
    return 3 * fwd


def _chip_peak_flops():
    """bf16 peak of the attached chip, or None when not a recognized TPU
    (no fabricated MFU on CPU fallback / unknown accelerators)."""
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for tag, peak in _CHIP_PEAK_BF16.items():
        if tag in kind:
            return peak
    return None


def _run_steps(exe, prog, feed, loss_var, steps, warmup):
    """Shared measurement loop: warmup + sync, then a timed window of
    async-dispatched steps (each consumes the previous step's donated
    state; losses are device futures materialized once at the end — how
    a real training loop behaves, keeping host/tunnel latency off the
    critical path)."""
    for _ in range(warmup):
        out = exe.run(prog, feed=feed, fetch_list=[loss_var])
    np.asarray(out[0])
    t0 = time.perf_counter()
    losses = [exe.run(prog, feed=feed, fetch_list=[loss_var],
                      return_numpy=False)[0] for _ in range(steps)]
    vals = [float(np.asarray(l).reshape(-1)[0]) for l in losses]
    dt = time.perf_counter() - t0
    assert np.isfinite(vals).all()
    return dt, vals[-1]


def _measure_ernie(batch, seq, preds, cfg, steps, warmup,
                   scan_window=None):
    """samples/sec of the flagship step at one batch size; fresh state.

    Returns (samples_per_sec, dt, steps, info): the dispatch-loop number
    (steps = the step count behind dt, for FLOP accounting), plus —
    when scan_window is set — a fused Executor.run_steps window (ONE
    device program scanning `scan_window` distinct batches: the
    production training-loop shape, host/tunnel dispatch off the
    critical path). The better of the two is the reported throughput;
    info records both for the headline JSON."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import bert
    from paddle_tpu import optimizer
    from paddle_tpu.framework.scope import Scope, scope_guard

    main_prog, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch, seq, preds,
        optimizer_fn=lambda loss: optimizer.Adam(1e-4).minimize(loss))
    scope = Scope()
    info = {}
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.synthetic_batch(cfg, batch, seq, preds)
        feed = {k: jax.device_put(np.asarray(v)) for k, v in feed.items()}
        dt, loss = _run_steps(exe, main_prog, feed, fetch["loss"], steps,
                              warmup)
        assert np.isfinite(loss), "non-finite loss in benchmark"
        sps = batch * steps / dt
        info["dispatch_loop_sps"] = round(sps, 2)
        if scan_window:
            from paddle_tpu.models import bert as bert_mod
            # pre-staged on device like the dispatch loop's feed — the
            # timed window must measure the fused program, not the link
            batches = [bert_mod.synthetic_batch(cfg, batch, seq, preds,
                                                seed=i)
                       for i in range(scan_window)]
            stacked = {k: jax.device_put(np.stack([b[k] for b in batches]))
                       for k in feed}
            loss_var = fetch["loss"]
            out = exe.run_steps(main_prog, feed=stacked,
                                fetch_list=[loss_var])   # compile+warm
            t0 = time.perf_counter()
            out = exe.run_steps(main_prog, feed=stacked,
                                fetch_list=[loss_var])
            dts = time.perf_counter() - t0
            assert np.isfinite(np.asarray(out[0])).all()
            scan_sps = batch * scan_window / dts
            info["scan_window_sps"] = round(scan_sps, 2)
            if scan_sps > sps:
                sps, dt, steps = scan_sps, dts, scan_window
    return sps, dt, steps, info


def measure_headline():
    """Measure the flagship number FIRST; returns the headline JSON str."""
    from paddle_tpu.models import bert

    on_tpu = _on_tpu()
    # BERT/ERNIE-base, seq 128 — bf16 on TPU; tiny shapes on CPU fallback
    if on_tpu:
        batch, seq, preds = 128, 128, 20
        cfg = bert.bert_base(dtype="bfloat16")
        steps, warmup, window = 10, 3, 20
    else:
        batch, seq, preds = 8, 64, 8
        cfg = bert.BertConfig(vocab_size=8192, hidden_size=256,
                              num_layers=4, num_heads=4, ff_size=1024,
                              max_position=128)
        steps, warmup, window = 5, 2, 5

    sps, dt, nsteps, info = _measure_ernie(batch, seq, preds, cfg, steps,
                                           warmup, scan_window=window)
    best = (batch, sps, dt, nsteps, info)

    def headline_json(b):
        bbatch, sps_, dt_, bsteps, binfo = b
        result = {
            "metric": HEADLINE_METRIC,
            "value": round(sps_, 2),
            "unit": "samples/sec/chip",
            "vs_baseline": round(sps_ / REFERENCE_SAMPLES_PER_SEC, 3),
            "batch": bbatch,
        }
        result.update(binfo)
        peak = _chip_peak_flops()
        if peak is not None:
            result["mfu"] = round(
                bert_train_flops(cfg, bbatch, seq, preds) * bsteps / dt_ /
                peak, 4)
        return json.dumps(result)

    # bank the measured number NOW: if the batch-256 attempt below wedges
    # the fabric, the deadline watchdog still ships this headline
    _STATE["headline"] = headline_json(best)
    if on_tpu and _elapsed() > 0.45 * _DEADLINE_S:
        # cold-cache run already burned the budget on the batch-128
        # compiles — skip the optional attempt so secondaries (pallas
        # check, resnet) still fit before the deadline
        print("skipping batch-256 attempt at %.0fs elapsed" % _elapsed(),
              file=sys.stderr)
    elif on_tpu:
        # larger batches amortize per-step overhead and fill the MXU
        # better; keep whichever config sustains more samples/sec.
        # Guarded: an OOM/compile failure on 256 must not cost the
        # already-measured 128 result.
        _STATE["stage"] = "headline-batch256"
        try:
            s256, d256, n256, i256 = _measure_ernie(
                256, seq, preds, cfg, max(steps // 2, 5), warmup,
                scan_window=10)
            if s256 > best[1]:
                best = (256, s256, d256, n256, i256)
                _STATE["headline"] = headline_json(best)
        except Exception as e:  # pragma: no cover
            print("batch-256 attempt failed: %r" % (e,), file=sys.stderr)

    return headline_json(best)


def _bench_section(build_fn, feed, items_per_step, metric, unit,
                   ref=None, steps=20, warmup=3):
    """Shared secondary-section scaffold: own scope (state must not stay
    resident in HBM after the section), one pre-staged device_put of the
    batch (production DataLoader double-buffers to HBM ahead of compute;
    re-transferring each step would only measure the link), timed window
    via _run_steps."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.framework.scope import Scope, scope_guard
    main_prog, startup, _feeds, fetch = build_fn()
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = {k: jax.device_put(np.asarray(v)) for k, v in feed.items()}
        dt, _ = _run_steps(exe, main_prog, feed, fetch["loss"], steps,
                           warmup)
    rate = items_per_step * steps / dt
    line = {"metric": metric, "value": round(rate, 2), "unit": unit}
    if ref is not None:
        line["vs_baseline"] = round(rate / ref, 3)
    return json.dumps(line)



def bench_resnet():
    from paddle_tpu.models import resnet
    from paddle_tpu import optimizer
    on_tpu = _on_tpu()
    batch = 128 if on_tpu else 4
    shape = (3, 224, 224) if on_tpu else (3, 32, 32)
    steps, warmup = (20, 3) if on_tpu else (3, 1)
    rng = np.random.RandomState(0)
    feed = {"image": rng.rand(batch, *shape).astype(np.float32),
            "label": rng.randint(0, 1000, (batch, 1)).astype(np.int64)}
    return _bench_section(
        lambda: resnet.resnet_train_program(
            depth=50, class_dim=1000, image_shape=shape,
            optimizer_fn=lambda l: optimizer.Momentum(0.1, 0.9)
            .minimize(l)),
        feed, batch, "ResNet-50 train images/sec/chip", "images/sec/chip",
        ref=REFERENCE_RESNET_IPS, steps=steps, warmup=warmup)


def bench_ernie2():
    """ERNIE 2.0 multi-task pretrain (task-sampling schedule, base
    geometry; the large config is pod-scale and exceeds one chip's HBM
    with Adam state)."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import bert
    from paddle_tpu import optimizer
    on_tpu = _on_tpu()
    if on_tpu:
        batch, seq, preds = 128, 128, 20
        cfg = bert.bert_base(dtype="bfloat16")
        steps, warmup = 15, 3
    else:
        batch, seq, preds = 4, 32, 4
        cfg = bert.BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                              num_heads=2, ff_size=128, max_position=64)
        steps, warmup = 3, 1
    from paddle_tpu.framework.scope import Scope, scope_guard
    main_prog, startup, feeds, fetch = bert.ernie2_multitask_program(
        cfg, batch, seq, preds, dynamic_task_weights=True,
        optimizer_fn=lambda loss: optimizer.Adam(1e-4).minimize(loss))
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.ernie2_synthetic_batch(cfg, batch, seq, preds)
        feed = {k: jax.device_put(np.asarray(v)) for k, v in feed.items()}
        sched = list(bert.ernie2_task_schedule(steps + warmup,
                                               (1., 1., 1.)))
        staged = [dict(feed, task_weight=jax.device_put(v))
                  for v in sched]
        for i in range(warmup):
            out = exe.run(main_prog, feed=staged[i],
                          fetch_list=[fetch["loss"]])
        np.asarray(out[0])
        t0 = time.perf_counter()
        ls = [exe.run(main_prog, feed=staged[warmup + i],
                      fetch_list=[fetch["loss"]], return_numpy=False)[0]
              for i in range(steps)]
        vals = [float(np.asarray(l).reshape(-1)[0]) for l in ls]
        dt = time.perf_counter() - t0
    assert np.isfinite(vals).all()
    sps = batch * steps / dt
    return json.dumps({
        "metric": "ERNIE-2.0 multitask pretrain samples/sec/chip",
        "value": round(sps, 2), "unit": "samples/sec/chip",
        "vs_baseline": round(sps / REFERENCE_SAMPLES_PER_SEC, 3)})


def bench_transformer():
    """Transformer-base NMT (BASELINE configs[1]): WMT en-de geometry,
    label-smoothed CE, Adam."""
    from paddle_tpu.models import transformer as tr
    from paddle_tpu import optimizer
    on_tpu = _on_tpu()
    if on_tpu:
        cfg = tr.TransformerConfig()          # base: d512/ff2048/6L/8H
        batch, src_len, trg_len = 64, 64, 64
        steps, warmup = 15, 3
    else:
        cfg = tr.TransformerConfig(src_vocab=512, trg_vocab=512,
                                   d_model=64, d_inner=128, n_head=2,
                                   n_layer=2)
        batch, src_len, trg_len = 4, 16, 16
        steps, warmup = 3, 1
    return _bench_section(
        lambda: tr.transformer_train_program(
            cfg, src_len, trg_len,
            optimizer_fn=lambda l: optimizer.Adam(1e-4).minimize(l)),
        tr.synthetic_batch(cfg, batch, src_len, trg_len),
        batch * trg_len, "Transformer-base NMT train tokens/sec/chip",
        "tokens/sec/chip", steps=steps, warmup=warmup)


def bench_deepfm():
    """DeepFM CTR (BASELINE configs[3]): high-dim sparse embedding."""
    from paddle_tpu.models import deepfm
    from paddle_tpu import optimizer
    on_tpu = _on_tpu()
    feature_dim = 1000000 if on_tpu else 5000
    batch = 2048 if on_tpu else 64
    steps, warmup = (20, 3) if on_tpu else (3, 1)
    return _bench_section(
        lambda: deepfm.deepfm_train_program(
            feature_dim=feature_dim,
            optimizer_fn=lambda l: optimizer.Adam(1e-3).minimize(l)),
        deepfm.synthetic_batch(batch, feature_dim=feature_dim),
        batch, "DeepFM CTR train examples/sec/chip", "examples/sec/chip",
        steps=steps, warmup=warmup)


def bench_gpt_longctx():
    """End-to-end long-context training: GPT causal LM at T=4096 bf16
    through the Pallas flash kernel with rematerialized blocks — the
    single-chip e2e evidence for the long-sequence story (the ring/
    Ulysses paths shard this same model over an sp mesh). Reports
    tokens/sec and MFU."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import gpt
    from paddle_tpu import optimizer
    from paddle_tpu.framework.scope import Scope, scope_guard

    on_tpu = _on_tpu()
    if on_tpu:
        cfg = gpt.GPTConfig(vocab_size=32000, hidden_size=768,
                            num_layers=12, num_heads=12, ff_size=3072,
                            max_position=4096, dropout=0.0,
                            dtype="bfloat16", attn_impl="flash",
                            recompute=True)
        batch, seq, steps, warmup = 2, 4096, 6, 2
    else:
        cfg = gpt.GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                            num_heads=2, ff_size=128, max_position=256,
                            dropout=0.0)
        batch, seq, steps, warmup = 1, 128, 2, 1
    main, startup, feeds, fetch = gpt.gpt_pretrain_program(
        cfg, batch, seq,
        optimizer_fn=lambda l: optimizer.Adam(1e-4).minimize(l))
    feed = gpt.synthetic_batch(cfg, batch, seq)
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = {k: jax.device_put(np.asarray(v)) for k, v in feed.items()}
        dt, loss = _run_steps(exe, main, feed, fetch["loss"], steps,
                              warmup)
    tps = batch * seq * steps / dt
    line = {"metric": "GPT long-context train tokens/sec/chip (T=%d)"
            % seq, "value": round(tps, 1), "unit": "tokens/sec/chip"}
    peak = _chip_peak_flops()
    if peak is not None:
        line["mfu"] = round(
            gpt_train_flops(cfg, batch, seq) * steps / dt / peak, 4)
    return json.dumps(line)


def _timed_attn_tokens(loss_fn, q, k, v, b, t, steps):
    """Shared fwd+bwd attention timing harness (longseq + flashtune):
    warm compile, then `steps` grad evaluations; returns tokens/sec."""
    import jax
    g = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))
    jax.block_until_ready(g(q, k, v))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = g(q, k, v)
    jax.block_until_ready(out)
    return b * t * steps / (time.perf_counter() - t0)


def bench_flashtune():
    """Flash-attention block-size sweep at the long-context shape
    (T=4096 bf16 fwd+bwd): reports tokens/sec per (block_q, block_k) and
    the winner — apply fleet-wide via PADDLE_TPU_FLASH_BLOCK_Q/_K."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa

    on_tpu = _on_tpu()
    if on_tpu:
        b, h, t, d, steps = 4, 12, 4096, 64, 6
        grid = [(128, 128), (128, 256), (256, 128), (256, 256),
                (128, 512), (512, 128), (512, 512)]
    else:
        b, h, t, d, steps = 1, 2, 256, 32, 2
        grid = [(128, 128), (128, 256)]
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    scale = 1.0 / np.sqrt(d)
    interp = not on_tpu

    results = {}
    for bq, bk in grid:
        def loss(q, k, v, bq=bq, bk=bk):
            o = fa.flash_attention(q, k, v, scale=scale, causal=True,
                                   block_q=bq, block_k=bk,
                                   interpret=interp)
            return jnp.sum(o.astype(jnp.float32))
        try:
            results["%dx%d" % (bq, bk)] = round(
                _timed_attn_tokens(loss, q, k, v, b, t, steps), 1)
        except Exception as e:  # VMEM overflow at big tiles etc.
            results["%dx%d" % (bq, bk)] = "failed: %r" % (e,)
    numeric = {kk: vv for kk, vv in results.items()
               if isinstance(vv, float)}
    best = max(numeric, key=numeric.get) if numeric else None
    return json.dumps({"metric": "flash-attention block tuning T=%d" % t,
                       "unit": "tokens/sec/chip", "results": results,
                       "best": best,
                       "value": numeric.get(best, 0.0)})


def bench_beam_decode():
    """Transformer-NMT beam-search decode tokens/sec (VERDICT r4 next
    #10; reference treats decode as first-class: beam_search_op.cc).
    Measures the cached path: per-step KV caches, beams as a flattened
    static (N*B) batch, topk+gather frontier."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import transformer as tr
    from paddle_tpu.framework.scope import Scope, scope_guard

    on_tpu = _on_tpu()
    if on_tpu:
        cfg = tr.TransformerConfig()          # base geometry
        # t_max bounds the unrolled per-step graph: 32 keeps trace+compile
        # inside the bench's deadline reserve (the section runs after the
        # banked headline, so a blowout only costs this optional line)
        batch, src_len, t_max, beam, steps = 16, 64, 32, 4, 6
    else:
        cfg = tr.TransformerConfig(src_vocab=512, trg_vocab=512,
                                   d_model=64, d_inner=128, n_head=2,
                                   n_layer=2)
        batch, src_len, t_max, beam, steps = 2, 16, 8, 2, 2
    main, startup, feeds, fetch = tr.beam_search_decode_program(
        cfg, src_len, t_max, beam_size=beam)
    rng = np.random.RandomState(0)
    feed = {"src_ids": rng.randint(
                0, cfg.src_vocab, (batch, src_len, 1)).astype(np.int64),
            "src_mask": np.ones((batch, src_len, 1), np.float32)}
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        fetch_list = [fetch["out_ids"], fetch["scores"]]
        out = exe.run(main, feed=feed, fetch_list=fetch_list)  # compile
        assert np.isfinite(np.asarray(out[1])).all()
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main, feed=feed, fetch_list=fetch_list,
                          return_numpy=False)
        np.asarray(out[1])
        dt = time.perf_counter() - t0
    tps = batch * t_max * steps / dt
    return json.dumps({
        "metric": "Transformer-NMT beam-search decode tokens/sec/chip",
        "value": round(tps, 1), "unit": "tokens/sec/chip",
        "beam": beam, "batch": batch, "out_len": t_max})


def bench_bucketed_training():
    """Length-bucketed training vs max-len padding on a skewed length
    distribution (VERDICT r4 next #4): same samples, same model; the
    bucketed pass pads each batch to its bucket instead of max_len.
    The reference's LoD kernels pay zero padding (sequence_pool_op.h:29)
    — bucketing is the dense+lengths answer, and the speedup is the MXU
    work the max-len pad was wasting."""
    import paddle_tpu as pt
    from paddle_tpu import layers, optimizer
    from paddle_tpu.framework.scope import Scope, scope_guard
    from paddle_tpu.dataset.dataset_api import InMemoryDataset

    on_tpu = _on_tpu()
    if on_tpu:
        vocab, hidden, max_len, batch, n_batches = 8192, 512, 256, 128, 24
        buckets = (32, 64, 128, 256)
        n_layers = 4
    else:
        vocab, hidden, max_len, batch, n_batches = 512, 32, 64, 8, 6
        buckets = (16, 32, 64)
        n_layers = 2
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(batch * n_batches):
        # skewed: bulk short, long tail — the regime where max-len
        # padding wastes the most
        ln = int(np.clip(rng.geometric(1.0 / (max_len // 8)), 4, max_len))
        samples.append({
            "ids": rng.randint(1, vocab, (ln,)).astype(np.int64),
            "label": rng.randint(0, 2, (1,)).astype(np.int64)})

    def build():
        main, startup = pt.Program(), pt.Program()
        with pt.program_guard(main, startup):
            ids = layers.data("ids", [-1], dtype="int64")
            label = layers.data("label", [1], dtype="int64")
            emb = layers.embedding(ids, size=[vocab, hidden])
            mask = layers.cast(
                layers.not_equal(ids, layers.zeros_like(ids)), "float32")
            h = emb
            for _ in range(n_layers):
                h = layers.fc(h, hidden, num_flatten_dims=2, act="gelu")
            pooled = layers.reduce_sum(
                h * layers.unsqueeze(mask, [2]), dim=1)
            logits = layers.fc(pooled, size=2)
            loss = layers.reduce_mean(
                layers.softmax_with_cross_entropy(logits, label))
            optimizer.Adam(1e-3).minimize(loss)
        return main, startup, loss

    def run_pass(bucket_list):
        ds = InMemoryDataset()
        ds.set_batch_size(batch)
        ds._samples = list(samples)
        ds.set_length_buckets(bucket_list, by="ids")
        main, startup, loss = build()
        with scope_guard(Scope()):
            exe = pt.Executor()
            exe.run(startup)
            exe.train_from_dataset(main, ds, fetch_list=[loss])  # compile
            best_dt = None
            for _ in range(2):   # best-of-2: host contention insurance
                t0 = time.perf_counter()
                steps, last = exe.train_from_dataset(main, ds,
                                                     fetch_list=[loss])
                dt = time.perf_counter() - t0
                assert np.isfinite(np.asarray(last[0])).all()
                best_dt = dt if best_dt is None else min(best_dt, dt)
        return len(samples) / best_dt

    bucketed_sps = run_pass(buckets)
    maxlen_sps = run_pass((max_len,))   # every batch padded to max_len
    return json.dumps({
        "metric": "length-bucketed training speedup vs max-len padding",
        "value": round(bucketed_sps / maxlen_sps, 3), "unit": "x",
        "bucketed_sps": round(bucketed_sps, 1),
        "maxlen_sps": round(maxlen_sps, 1)})


def pallas_selfcheck():
    """Pallas-vs-XLA oracle ON THE REAL CHIP — the only coverage of the
    compiled Mosaic kernels (CPU tests run interpret mode and the
    <128-block guards route small shapes to XLA). Flash attention: fwd +
    backward in both mask modes (causal, additive padding mask) at
    T=128/256, f32 and bf16 (SURVEY §5 / round-3 Weak #5). Plus the
    PR-7 kernel library: blockwise CE, the fused MLM head, fused Adam
    and fused LayerNorm, each fwd+bwd against its pure-JAX reference."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa

    # PADDLE_TPU_BENCH_SELFCHECK_INTERPRET=1: run the same check in
    # interpret mode off-TPU so the check logic itself is testable on CPU.
    interp = os.environ.get(
        "PADDLE_TPU_BENCH_SELFCHECK_INTERPRET") == "1"
    if not interp and not _on_tpu():
        return json.dumps({"metric": "pallas_check", "skipped": "no TPU"})

    rng = np.random.RandomState(0)
    worst = {}
    for dtype, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 1e-2)):
        for t in (128, 256):
            b, h, d = 2, 4, 64
            q = jnp.asarray(rng.randn(b, h, t, d), dtype)
            k = jnp.asarray(rng.randn(b, h, t, d), dtype)
            v = jnp.asarray(rng.randn(b, h, t, d), dtype)
            scale = 1.0 / np.sqrt(d)
            # additive padding mask: last quarter of keys masked out
            pad = np.zeros((b, 1, 1, t), np.float32)
            pad[..., 3 * t // 4:] = -1e9
            # fixed random cotangent shared by both implementations
            w = jnp.asarray(rng.randn(b, h, t, d).astype(np.float32))
            for mode, mask, causal in (("causal", None, True),
                                       ("padmask", jnp.asarray(pad, dtype),
                                        False)):
                def pallas_loss(q, k, v, mask=mask, causal=causal):
                    o = fa.flash_attention(q, k, v, mask=mask, scale=scale,
                                           causal=causal, interpret=interp)
                    return jnp.sum(o.astype(jnp.float32) * w)

                def xla_loss(q, k, v, mask=mask, causal=causal):
                    o = fa._xla_attention(q, k, v, mask, scale, causal)
                    return jnp.sum(o.astype(jnp.float32) * w)

                grads_p = jax.jit(jax.grad(pallas_loss,
                                           argnums=(0, 1, 2)))(q, k, v)
                grads_x = jax.jit(jax.grad(xla_loss,
                                           argnums=(0, 1, 2)))(q, k, v)
                o_p = fa.flash_attention(q, k, v, mask=mask, scale=scale,
                                         causal=causal, interpret=interp)
                o_x = fa._xla_attention(q, k, v, mask, scale, causal)
                abs_errs, rel_errs = [], []
                for a, b_ in [(o_p, o_x)] + list(zip(grads_p, grads_x)):
                    diff = float(jnp.max(jnp.abs(
                        a.astype(jnp.float32) - b_.astype(jnp.float32))))
                    mag = float(jnp.max(jnp.abs(b_.astype(jnp.float32))))
                    abs_errs.append(diff)
                    # normalize by the oracle's dynamic range: a bf16
                    # result is only representable to ~0.4% of its
                    # magnitude, so absolute error alone would flag
                    # 1-ulp differences on large-magnitude grads
                    rel_errs.append(diff / max(mag, 1.0))
                key = "%s_T%d_%s" % (np.dtype(dtype).name, t, mode)
                worst[key] = {"max_abs_err": round(max(abs_errs), 8),
                              "max_rel_err": round(max(rel_errs), 8),
                              "tol": tol, "ok": max(rel_errs) < tol}

    def _cmp(key, pairs, tol):
        abs_errs, rel_errs = [], []
        for a, b_ in pairs:
            a = jnp.asarray(a, jnp.float32)
            b_ = jnp.asarray(b_, jnp.float32)
            diff = float(jnp.max(jnp.abs(a - b_)))
            abs_errs.append(diff)
            rel_errs.append(diff / max(float(jnp.max(jnp.abs(b_))), 1.0))
        worst[key] = {"max_abs_err": round(max(abs_errs), 8),
                      "max_rel_err": round(max(rel_errs), 8),
                      "tol": tol, "ok": max(rel_errs) < tol}

    # ---- PR-7 kernel library: CE / fused head / adam / layernorm ----
    from paddle_tpu.ops.pallas.blockwise_ce import (
        blockwise_softmax_cross_entropy, fused_mlm_head_loss)
    from paddle_tpu.ops.pallas.fused_adam import fused_adam
    from paddle_tpu.ops.pallas.layer_norm import fused_layer_norm

    t, v, d = 256, 1024, 256
    labels = jnp.asarray(rng.randint(0, v, (t,)), jnp.int32)
    cot = jnp.asarray(rng.randn(t).astype(np.float32))
    for dtype, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 1e-2)):
        logits = jnp.asarray(rng.randn(t, v), dtype)

        def ce_ref(lg):
            logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            return -jnp.take_along_axis(logp, labels[:, None],
                                        axis=1)[:, 0]

        def ce_pal(lg):
            return blockwise_softmax_cross_entropy(lg, labels,
                                                   interpret=interp)
        gp = jax.jit(jax.grad(lambda lg: jnp.sum(ce_pal(lg) * cot)))
        gx = jax.jit(jax.grad(lambda lg: jnp.sum(ce_ref(lg) * cot)))
        _cmp("ce_%s" % np.dtype(dtype).name,
             [(ce_pal(logits), ce_ref(logits)), (gp(logits), gx(logits))],
             tol)

        hid = jnp.asarray(rng.randn(t, d) * 0.2, dtype)
        w_ = jnp.asarray(rng.randn(d, v) * 0.1, dtype)

        def head_ref(h, w):
            return ce_ref((h.astype(jnp.float32) @
                           w.astype(jnp.float32)).astype(dtype))

        def head_pal(h, w):
            return fused_mlm_head_loss(h, w, labels, interpret=interp)
        hp = jax.jit(jax.grad(
            lambda h, w: jnp.sum(head_pal(h, w) * cot), argnums=(0, 1)))
        hx = jax.jit(jax.grad(
            lambda h, w: jnp.sum(head_ref(h, w) * cot), argnums=(0, 1)))
        _cmp("mlm_head_%s" % np.dtype(dtype).name,
             [(head_pal(hid, w_), head_ref(hid, w_))] +
             list(zip(hp(hid, w_), hx(hid, w_))), tol)

    n = 65536
    p_ = jnp.asarray(rng.randn(n).astype(np.float32))
    g_ = jnp.asarray(rng.randn(n).astype(np.float32))
    m1 = jnp.asarray(np.abs(rng.randn(n)).astype(np.float32) * 0.1)
    m2 = jnp.asarray(np.abs(rng.randn(n)).astype(np.float32) * 0.1)
    lr_t = jnp.float32(0.01)
    pal = jax.jit(lambda: fused_adam(p_, g_, m1, m2, lr_t,
                                     interpret=interp))()
    m1r = 0.9 * m1 + 0.1 * g_
    m2r = 0.999 * m2 + 0.001 * g_ * g_
    ref = (p_ - lr_t * m1r / (jnp.sqrt(m2r) + 1e-8), m1r, m2r)
    _cmp("adam_f32", list(zip(pal, ref)), 1e-5)

    r, c = 256, 512
    x_ = jnp.asarray(rng.randn(r, c).astype(np.float32))
    sc = jnp.asarray(rng.randn(c).astype(np.float32))
    bi = jnp.asarray(rng.randn(c).astype(np.float32))
    wln = jnp.asarray(rng.randn(r, c).astype(np.float32))

    def ln_ref(x, sc, bi):
        m = jnp.mean(x, -1, keepdims=True)
        vv = jnp.var(x, -1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(vv + 1e-5) * sc[None, :] + bi

    def ln_pal(x, sc, bi):
        return fused_layer_norm(x, sc, bi, interpret=interp)
    lp = jax.jit(jax.grad(lambda *a: jnp.sum(ln_pal(*a) * wln),
                          argnums=(0, 1, 2)))
    lx = jax.jit(jax.grad(lambda *a: jnp.sum(ln_ref(*a) * wln),
                          argnums=(0, 1, 2)))
    _cmp("layer_norm_f32",
         [(ln_pal(x_, sc, bi), ln_ref(x_, sc, bi))] +
         list(zip(lp(x_, sc, bi), lx(x_, sc, bi))), 1e-5)

    return json.dumps({"metric": "pallas_check", "checks": worst,
                       "ok": all(c["ok"] for c in worst.values())})


def bench_longseq_attention():
    """Long-context attention throughput: the Pallas flash kernel vs the
    XLA fused reference at T=4096 bf16, fwd+bwd (grad wrt q,k,v). The
    flash path never materializes the (T,T) scores in HBM — this section
    is the single-chip evidence for the long-sequence story (SURVEY
    §2.7's ring/Ulysses paths shard the same kernel over a mesh)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas import flash_attention as fa

    on_tpu = _on_tpu()
    if on_tpu:
        b, h, t, d, steps = 4, 12, 4096, 64, 8
    else:
        b, h, t, d, steps = 1, 2, 256, 32, 2
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, h, t, d), jnp.bfloat16)
    scale = 1.0 / np.sqrt(d)
    interp = not on_tpu

    def timed(loss_fn):
        return _timed_attn_tokens(loss_fn, q, k, v, b, t, steps)

    def flash_loss(q, k, v):
        o = fa.flash_attention(q, k, v, scale=scale, causal=True,
                               interpret=interp)
        return jnp.sum(o.astype(jnp.float32))

    def xla_loss(q, k, v):
        o = fa._xla_attention(q, k, v, None, scale, True)
        return jnp.sum(o.astype(jnp.float32))

    line = {"metric": "flash-attention T=%d bf16 fwd+bwd tokens/sec" % t,
            "unit": "tokens/sec/chip"}
    line["value"] = round(timed(flash_loss), 1)
    try:
        xla_tps = timed(xla_loss)
        line["xla_tokens_per_sec"] = round(xla_tps, 1)
        line["speedup_vs_xla"] = round(line["value"] / xla_tps, 3)
    except Exception as e:  # XLA OOMs on the (T,T) buffers first
        line["xla_tokens_per_sec"] = "failed: %r" % (e,)
    return json.dumps(line)


def run_all():
    deadline = _arm_deadline()
    # NOTE: no jax import before a probe succeeds — the probe-subprocess
    # isolation exists precisely because plugin discovery in THIS process
    # can wedge on a sick fabric with no way to retry.
    _STATE["stage"] = "backend-probe"
    platforms, err, attach_verdict, attach_timeline = _probe_backend()
    if err is not None:
        # never again a zero-signal round: the CPU microbench suite
        # ships a perf verdict as a secondary line by DEFAULT (r3–r5
        # carried nothing because this was opt-in), and the (error)
        # headline classifies the attach failure so the driver can
        # tell a fabric hang (retry next round) from a deterministic
        # init error (fix the environment first)
        micro_ok = False
        if _micro_enabled():
            _STATE["stage"] = "micro-fallback"
            line = _run_micro_fallback()
            if line is not None:
                _STATE["lines"].append(line)
                micro_ok = True
        head = json.loads(_error_headline(err))
        head["attach_verdict"] = attach_verdict
        head["attach_timeline"] = attach_timeline
        head["micro_fallback"] = micro_ok
        _STATE["headline"] = json.dumps(head)
        _flush_and_exit(0)
    sys.stderr.write("backend: %s\n" % ",".join(platforms))
    try:
        # persistent compile cache: if a previous bench attempt died
        # mid-compile (driver timeout, fabric blip), the retry skips the
        # compiles it already paid for
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("PADDLE_TPU_COMPILE_CACHE",
                                         "/tmp/paddle_tpu_jax_cache"))
        _apply_platform_override()
    except Exception:  # pragma: no cover
        pass

    # 1) headline FIRST — nothing may starve it
    _STATE["stage"] = "headline"
    try:
        head = json.loads(measure_headline())
        head["attach_verdict"] = attach_verdict
        head["attach_timeline"] = attach_timeline
        _STATE["headline"] = json.dumps(head)
    except Exception as e:
        head = json.loads(_error_headline("headline failed: %r" % (e,)))
        head["attach_verdict"] = attach_verdict
        head["attach_timeline"] = attach_timeline
        _STATE["headline"] = json.dumps(head)
        _flush_and_exit(0)

    # 2) secondaries — buffered, each fenced
    # pallas_check (kernel correctness, merged into the headline) runs
    # BEFORE the optional throughput extras, so a deadline firing during
    # transformer/deepfm can only drop optional lines
    for name, fn in (("resnet", bench_resnet), ("ernie2", bench_ernie2),
                     ("pallas_check", pallas_selfcheck),
                     ("longseq", bench_longseq_attention),
                     ("bucketed", bench_bucketed_training),
                     ("gpt_longctx", bench_gpt_longctx),
                     ("transformer", bench_transformer),
                     ("beam_decode", bench_beam_decode),
                     ("deepfm", bench_deepfm),
                     ("flashtune", bench_flashtune)):
        _STATE["stage"] = name
        try:
            line = fn()
            _STATE["lines"].append(line)
            if name == "pallas_check":
                # a kernel-correctness regression must be visible in the
                # ONE line the driver parses, not only in a buffered
                # secondary
                parsed = json.loads(line)
                if "ok" in parsed:
                    head = json.loads(_STATE["headline"])
                    head["pallas_check_ok"] = parsed["ok"]
                    _STATE["headline"] = json.dumps(head)
        except Exception as e:  # pragma: no cover
            print("%s failed: %r" % (name, e), file=sys.stderr)

    deadline.cancel()
    _flush_and_exit(0)


def profile_headline():
    """Per-op attribution of the flagship step (profiler.profile_program
    runs it op-by-op eagerly — use for WHICH ops dominate, not absolute
    time) + the fused step's HLO dumped to /tmp for inspection. The
    input for SURVEY §6's profile analysis."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu import optimizer, profiler
    from paddle_tpu.models import bert
    from paddle_tpu.framework.scope import Scope, scope_guard

    on_tpu = _on_tpu()
    if on_tpu:
        batch, seq, preds = 128, 128, 20
        cfg = bert.bert_base(dtype="bfloat16")
    else:
        batch, seq, preds = 8, 64, 8
        cfg = bert.BertConfig(vocab_size=8192, hidden_size=256,
                              num_layers=4, num_heads=4, ff_size=1024,
                              max_position=128)
    main_prog, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch, seq, preds,
        optimizer_fn=lambda loss: optimizer.Adam(1e-4).minimize(loss))
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.synthetic_batch(cfg, batch, seq, preds)
        profiler.profile_program(main_prog, feed, repeat=2, top_k=25)
        hlo = exe.dump_hlo(main_prog, feed=feed,
                           fetch_list=[fetch["loss"]])
        path = "/tmp/paddle_tpu_headline_hlo.txt"
        text = "\n\n".join("==== %s ====\n%s" % (k, v)
                           for k, v in hlo.items()) \
            if isinstance(hlo, dict) else str(hlo)
        with open(path, "w") as f:
            f.write(text)
        print("fused-step HLO written to %s (%d bytes)"
              % (path, len(text)))
        dot_inventory(text)


def dot_inventory(hlo_text, top_k=20):
    """Classify every dot_general in the fused step's HLO by operand
    dtypes and analytic FLOPs — the r4 bf16 audit (which found the f32
    vocab-decode backward) as one command. Non-bf16 rows at the top of
    this table are the MFU attack surface: on TPU a DEFAULT-precision
    f32 dot runs the MXU at half rate (or worse, f32 passes)."""
    import re
    dots = []
    # the executor dumps StableHLO ("lowered" section):
    #   %54 = stablehlo.dot_general %a, %b, contracting_dims = [1] x [0],
    #     precision = [...] : (tensor<512x256xbf16>, tensor<256x256xbf16>)
    #     -> tensor<512x256xbf16>
    line_pat = re.compile(
        r"stablehlo\.dot_general([^:]*)contracting_dims = \[([\d, ]*)\]"
        r"[^:]*:\s*\(tensor<([^>]*)>,\s*tensor<([^>]*)>\)\s*->\s*"
        r"tensor<([^>]*)>", re.DOTALL)
    prec_pat = re.compile(r"precision = \[(\w+)")

    def parse_tensor(spec):
        parts = spec.split("x")
        return [int(p) for p in parts[:-1]], parts[-1]

    for m in line_pat.finditer(hlo_text):
        head, cdims, a_spec, b_spec, out_spec = m.groups()
        a, a_dt = parse_tensor(a_spec)
        b, b_dt = parse_tensor(b_spec)
        out, out_dt = parse_tensor(out_spec)
        pm = prec_pat.search(m.group(0))
        precision = pm.group(1) if pm else "DEFAULT"
        contract = 1
        for i in [int(x) for x in cdims.replace(" ", "").split(",") if x]:
            contract *= a[i] if i < len(a) else 1
        flops = 2.0 * float(np.prod(out or [1])) * contract
        dots.append({"out": "%sx%s" % ("x".join(map(str, out)), out_dt),
                     "lhs": "%sx%s" % ("x".join(map(str, a)), a_dt),
                     "rhs": "%sx%s" % ("x".join(map(str, b)), b_dt),
                     "bf16_operands": a_dt == "bf16" and b_dt == "bf16",
                     "precision": precision,
                     "gflops": round(flops / 1e9, 3)})
    if not dots:
        print("dot inventory: no dot() lines parsed (check HLO format)")
        return dots
    dots.sort(key=lambda d: -d["gflops"])
    total = sum(d["gflops"] for d in dots)
    nonbf = sum(d["gflops"] for d in dots if not d["bf16_operands"])
    print("\ndot_general inventory: %d dots, %.1f GFLOP total, "
          "%.1f GFLOP (%.1f%%) with non-bf16 operands"
          % (len(dots), total, nonbf, 100.0 * nonbf / max(total, 1e-9)))
    for d in dots[:top_k]:
        note = "" if d["bf16_operands"] else "   <-- NOT bf16"
        if d["precision"] != "DEFAULT":
            note += "  [precision=%s]" % d["precision"]
        print("  %8.2f GF  %s  %s x %s%s"
              % (d["gflops"], d["out"], d["lhs"], d["rhs"], note))
    return dots


def _apply_platform_override():
    """Honor an explicit JAX_PLATFORMS env override — the axon
    sitecustomize forces jax_platforms at import time, shadowing the env
    var. Shared by run_all and the section-mode CLI."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


if __name__ == "__main__":
    if len(sys.argv) > 1:
        _apply_platform_override()
    if len(sys.argv) > 1 and sys.argv[1] == "resnet":
        print(bench_resnet())
    elif len(sys.argv) > 1 and sys.argv[1] == "ernie2":
        print(bench_ernie2())
    elif len(sys.argv) > 1 and sys.argv[1] == "pallas":
        print(pallas_selfcheck())
    elif len(sys.argv) > 1 and sys.argv[1] == "longseq":
        print(bench_longseq_attention())
    elif len(sys.argv) > 1 and sys.argv[1] == "bucketed":
        print(bench_bucketed_training())
    elif len(sys.argv) > 1 and sys.argv[1] == "beam":
        print(bench_beam_decode())
    elif len(sys.argv) > 1 and sys.argv[1] == "flashtune":
        print(bench_flashtune())
    elif len(sys.argv) > 1 and sys.argv[1] == "gpt":
        print(bench_gpt_longctx())
    elif len(sys.argv) > 1 and sys.argv[1] == "transformer":
        print(bench_transformer())
    elif len(sys.argv) > 1 and sys.argv[1] == "deepfm":
        print(bench_deepfm())
    elif len(sys.argv) > 1 and sys.argv[1] == "micro":
        # section mode: run the CPU microbench suite directly (the same
        # suite run_all falls back to when the chip probe fails under
        # --micro / PADDLE_TPU_BENCH_MICRO=1)
        import bench_micro
        # empty argv: bench_micro.main must not see our "micro" token
        sys.exit(bench_micro.main(sys.argv[2:]))
    elif len(sys.argv) > 1 and sys.argv[1] == "profile":
        profile_headline()
    else:
        run_all()
