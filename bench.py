#!/usr/bin/env python
"""Headline benchmark: ERNIE/BERT-base pretrain samples/sec/chip.

BASELINE.json metric: "ERNIE-base pretrain samples/sec/chip". Runs the
flagship MLM+NSP train step (bf16 activations, fp32 master math, Adam,
fused attention) on the attached TPU chip. Prints the secondary ResNet-50
JSON line first, then the ERNIE headline JSON line LAST (the driver
parses the final line; on recognized TPUs it carries an "mfu" field).

vs_baseline: BASELINE.json carries no published numbers ("published": {}),
so the denominator is the reference's public era figure for this config:
PaddlePaddle fluid BERT-base seq128 pretraining throughput on one V100
(~50 samples/sec, PaddlePaddle/LARK benchmark tables) — i.e. vs_baseline
2.0 means 2x the reference's per-accelerator headline.
"""
import json
import os
import sys
import time

import numpy as np

REFERENCE_SAMPLES_PER_SEC = 50.0
# Secondary config (BASELINE metric string also names ResNet-50 images/sec):
# reference-era fluid ResNet-50 on one V100 ~ 360 images/sec.
REFERENCE_RESNET_IPS = 360.0

# bf16 peak FLOP/s per chip by device kind (MFU denominator)
_CHIP_PEAK_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,   # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,   # trillium
}


def _chip_peak_flops():
    """bf16 peak of the attached chip, or None when not a recognized TPU
    (no fabricated MFU on CPU fallback / unknown accelerators)."""
    import jax
    kind = getattr(jax.devices()[0], "device_kind", "").lower()
    for tag, peak in _CHIP_PEAK_BF16.items():
        if tag in kind:
            return peak
    return None


def bert_train_flops(cfg, batch, seq, preds):
    """Analytic per-step training FLOPs of the MLM+NSP model (matmul terms;
    fwd + ~2x for backward — the standard MFU accounting)."""
    d, L, ff = cfg.hidden_size, cfg.num_layers, cfg.ff_size
    tokens = batch * seq
    proj = 8 * tokens * d * d           # Q,K,V,O projections
    attn = 4 * batch * seq * seq * d    # scores + context matmuls
    ffn = 4 * tokens * d * ff           # two FFN matmuls
    fwd = L * (proj + attn + ffn)
    fwd += 2 * batch * preds * d * cfg.vocab_size   # MLM vocab decode
    fwd += 2 * batch * preds * d * d                # MLM transform
    return 3 * fwd


def _run_steps(exe, prog, feed, loss_var, steps, warmup):
    """Shared measurement loop: warmup + sync, then a timed window of
    async-dispatched steps (each consumes the previous step's donated
    state; losses are device futures materialized once at the end — how
    a real training loop behaves, keeping host/tunnel latency off the
    critical path)."""
    import numpy as np
    for _ in range(warmup):
        out = exe.run(prog, feed=feed, fetch_list=[loss_var])
    np.asarray(out[0])
    t0 = time.perf_counter()
    losses = [exe.run(prog, feed=feed, fetch_list=[loss_var],
                      return_numpy=False)[0] for _ in range(steps)]
    vals = [float(np.asarray(l).reshape(-1)[0]) for l in losses]
    dt = time.perf_counter() - t0
    assert np.isfinite(vals).all()
    return dt, vals[-1]


def bench_resnet():
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import resnet
    from paddle_tpu import optimizer
    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    batch = 128 if on_tpu else 4
    shape = (3, 224, 224) if on_tpu else (3, 32, 32)
    steps, warmup = (20, 3) if on_tpu else (3, 1)
    from paddle_tpu.framework.scope import Scope, scope_guard
    main_prog, startup, feeds, fetch = resnet.resnet_train_program(
        depth=50, class_dim=1000, image_shape=shape,
        optimizer_fn=lambda l: optimizer.Momentum(0.1, 0.9).minimize(l))
    # own scope: this model's params/optimizer state must not stay
    # resident in HBM while the headline (and its batch-256 attempt) runs
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"image": rng.rand(batch, *shape).astype(np.float32),
                "label": rng.randint(0, 1000,
                                     (batch, 1)).astype(np.int64)}
        # pre-stage to device once — in production the DataLoader's
        # background thread double-buffers batches to HBM ahead of
        # compute (reader.py); re-transferring the same batch each step
        # would only measure the link
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        dt, loss = _run_steps(exe, main_prog, feed, fetch["loss"], steps,
                              warmup)
    ips = batch * steps / dt
    print(json.dumps({"metric": "ResNet-50 train images/sec/chip",
                      "value": round(ips, 2), "unit": "images/sec/chip",
                      "vs_baseline": round(ips / REFERENCE_RESNET_IPS, 3)}))


def bench_ernie2():
    """ERNIE 2.0 multi-task pretrain (task-sampling schedule, base
    geometry; the large config is pod-scale and exceeds one chip's HBM
    with Adam state)."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import bert
    from paddle_tpu import optimizer
    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        batch, seq, preds = 128, 128, 20
        cfg = bert.bert_base(dtype="bfloat16")
        steps, warmup = 15, 3
    else:
        batch, seq, preds = 4, 32, 4
        cfg = bert.BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                              num_heads=2, ff_size=128, max_position=64)
        steps, warmup = 3, 1
    from paddle_tpu.framework.scope import Scope, scope_guard
    main_prog, startup, feeds, fetch = bert.ernie2_multitask_program(
        cfg, batch, seq, preds, dynamic_task_weights=True,
        optimizer_fn=lambda loss: optimizer.Adam(1e-4).minimize(loss))
    # own scope, like bench_resnet: free this state before the headline
    with scope_guard(Scope()):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.ernie2_synthetic_batch(cfg, batch, seq, preds)
        feed = {k: jax.device_put(np.asarray(v)) for k, v in feed.items()}
        sched = list(bert.ernie2_task_schedule(steps + warmup,
                                               (1., 1., 1.)))
        staged = [dict(feed, task_weight=jax.device_put(v))
                  for v in sched]
        for i in range(warmup):
            out = exe.run(main_prog, feed=staged[i],
                          fetch_list=[fetch["loss"]])
        np.asarray(out[0])
        t0 = time.perf_counter()
        ls = [exe.run(main_prog, feed=staged[warmup + i],
                      fetch_list=[fetch["loss"]], return_numpy=False)[0]
              for i in range(steps)]
        vals = [float(np.asarray(l).reshape(-1)[0]) for l in ls]
        dt = time.perf_counter() - t0
    assert np.isfinite(vals).all()
    sps = batch * steps / dt
    print(json.dumps({
        "metric": "ERNIE-2.0 multitask pretrain samples/sec/chip",
        "value": round(sps, 2), "unit": "samples/sec/chip",
        "vs_baseline": round(sps / REFERENCE_SAMPLES_PER_SEC, 3)}))


def _measure_ernie(batch, seq, preds, cfg, steps, warmup):
    """samples/sec of the flagship step at one batch size; fresh state."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import bert
    from paddle_tpu import optimizer
    from paddle_tpu.framework.scope import Scope, scope_guard

    main_prog, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch, seq, preds,
        optimizer_fn=lambda loss: optimizer.Adam(1e-4).minimize(loss))
    scope = Scope()
    with scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        feed = bert.synthetic_batch(cfg, batch, seq, preds)
        feed = {k: jax.device_put(np.asarray(v)) for k, v in feed.items()}
        dt, loss = _run_steps(exe, main_prog, feed, fetch["loss"], steps,
                              warmup)
    assert np.isfinite(loss), "non-finite loss in benchmark"
    return batch * steps / dt, dt


def main():
    import jax
    from paddle_tpu.models import bert

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    # BERT/ERNIE-base, seq 128 — bf16 on TPU; tiny shapes on CPU fallback
    if on_tpu:
        batch, seq, preds = 128, 128, 20
        cfg = bert.bert_base(dtype="bfloat16")
        steps, warmup = 20, 3
    else:
        batch, seq, preds = 8, 64, 8
        cfg = bert.BertConfig(vocab_size=8192, hidden_size=256,
                              num_layers=4, num_heads=4, ff_size=1024,
                              max_position=128)
        steps, warmup = 5, 2

    sps, dt = _measure_ernie(batch, seq, preds, cfg, steps, warmup)
    best = (batch, sps, dt, steps)
    if on_tpu:
        # larger batches amortize per-step overhead and fill the MXU
        # better; keep whichever config sustains more samples/sec.
        # Guarded: an OOM/compile failure on 256 must not cost the
        # already-measured 128 result.
        steps256 = max(steps // 2, 8)
        try:
            sps256, dt256 = _measure_ernie(256, seq, preds, cfg,
                                           steps256, warmup)
            if sps256 > best[1]:
                best = (256, sps256, dt256, steps256)
        except Exception as e:  # pragma: no cover
            print("batch-256 attempt failed: %r" % (e,), file=sys.stderr)

    bbatch, sps, dt, bsteps = best
    result = {
        "metric": "ERNIE-base pretrain samples/sec/chip",
        "value": round(sps, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps / REFERENCE_SAMPLES_PER_SEC, 3),
        "batch": bbatch,
    }
    peak = _chip_peak_flops()
    if peak is not None:
        mfu = bert_train_flops(cfg, bbatch, seq, preds) * bsteps / dt / \
            peak
        result["mfu"] = round(mfu, 4)
    print(json.dumps(result))


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "resnet":
        bench_resnet()
    elif len(sys.argv) > 1 and sys.argv[1] == "ernie2":
        bench_ernie2()
    else:
        # secondary configs first so the driver's last-line parse still
        # captures the ERNIE headline; never let them break the headline
        for fn in (bench_resnet, bench_ernie2):
            try:
                fn()
            except Exception as e:  # pragma: no cover
                print("%s failed: %r" % (fn.__name__, e), file=sys.stderr)
        main()
