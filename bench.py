#!/usr/bin/env python
"""Headline benchmark: ERNIE/BERT-base pretrain samples/sec/chip.

BASELINE.json metric: "ERNIE-base pretrain samples/sec/chip". Runs the
flagship MLM+NSP train step (bf16 activations, fp32 master math, Adam,
fused attention) on the attached TPU chip and prints ONE JSON line.

vs_baseline: BASELINE.json carries no published numbers ("published": {}),
so the denominator is the reference's public era figure for this config:
PaddlePaddle fluid BERT-base seq128 pretraining throughput on one V100
(~50 samples/sec, PaddlePaddle/LARK benchmark tables) — i.e. vs_baseline
2.0 means 2x the reference's per-accelerator headline.
"""
import json
import os
import sys
import time

import numpy as np

REFERENCE_SAMPLES_PER_SEC = 50.0


def main():
    import jax
    import paddle_tpu as pt
    from paddle_tpu.models import bert
    from paddle_tpu import optimizer

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    # BERT/ERNIE-base, seq 128 — bf16 on TPU; tiny shapes on CPU fallback
    if on_tpu:
        batch, seq, preds = 128, 128, 20
        cfg = bert.bert_base(dtype="bfloat16")
        steps, warmup = 20, 3
    else:
        batch, seq, preds = 8, 64, 8
        cfg = bert.BertConfig(vocab_size=8192, hidden_size=256,
                              num_layers=4, num_heads=4, ff_size=1024,
                              max_position=128)
        steps, warmup = 5, 2

    main_prog, startup, feeds, fetch = bert.bert_pretrain_program(
        cfg, batch, seq, preds,
        optimizer_fn=lambda loss: optimizer.Adam(1e-4).minimize(loss))
    exe = pt.Executor()
    exe.run(startup)
    feed = bert.synthetic_batch(cfg, batch, seq, preds)

    for _ in range(warmup):
        out = exe.run(main_prog, feed=feed, fetch_list=[fetch["loss"]])
    np.asarray(out[0])  # sync

    # steady state: JAX dispatch is async, so successive steps pipeline on
    # the chip (each consumes the previous step's donated state); losses are
    # device futures materialized once at the end — how a real training loop
    # behaves, and it keeps host/tunnel latency off the critical path.
    t0 = time.perf_counter()
    losses = []
    for _ in range(steps):
        out = exe.run(main_prog, feed=feed, fetch_list=[fetch["loss"]],
                      return_numpy=False)
        losses.append(out[0])
    loss_vals = [float(np.asarray(l).reshape(-1)[0]) for l in losses]
    dt = time.perf_counter() - t0
    loss = loss_vals[-1]

    sps = batch * steps / dt
    assert np.isfinite(loss), "non-finite loss in benchmark"
    result = {
        "metric": "ERNIE-base pretrain samples/sec/chip",
        "value": round(sps, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": round(sps / REFERENCE_SAMPLES_PER_SEC, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
