"""fluid.annotations parity (ref python/paddle/fluid/annotations.py)."""
import functools
import sys
import warnings

__all__ = ["deprecated"]


def deprecated(since, instead, extra_message=""):
    def decorator(func):
        err_msg = "API {0} is deprecated since {1}. Please use {2} " \
            "instead.".format(func.__name__, since, instead)
        if extra_message:
            err_msg += "\n" + extra_message

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(err_msg, DeprecationWarning, stacklevel=2)
            print(err_msg, file=sys.stderr)
            return func(*args, **kwargs)
        return wrapper
    return decorator
