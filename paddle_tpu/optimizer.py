"""Optimizers — graph-building API.

Reference parity: python/paddle/fluid/optimizer.py. ``minimize(loss)``
appends backward + update ops to the main program, exactly like the
reference; the Executor then compiles forward+backward+update into ONE XLA
computation with donated parameter buffers (in-place HBM updates).
"""
import math

from .framework.backward import append_backward
from .framework.program import (Program, Variable, default_main_program,
                                default_startup_program, program_guard)
from .framework import unique_name
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops
from . import clip as clip_mod


class Optimizer(object):
    _op_type = None

    def __init__(self, learning_rate, regularization=None, name=None,
                 grad_clip=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._accumulators = {}       # name -> {param_name: var}
        self._learning_rate_map = {}  # program -> lr var
        self.helper = None

    # ---- learning rate ----------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[id(program)] = self._learning_rate
            return
        if id(program) in self._learning_rate_map:
            return
        helper = LayerHelper("learning_rate")
        lr = helper.create_global_variable(
            name=unique_name.generate("learning_rate"), dtype="float32",
            shape=(1,), persistable=True)
        helper.set_variable_initializer(
            lr, ConstantInitializer(float(self._learning_rate)))
        self._learning_rate_map[id(program)] = lr

    def _global_learning_rate(self, program=None):
        program = program or default_main_program()
        return self._learning_rate_map.get(id(program))

    def _create_param_lr(self, param):
        lr = self._global_learning_rate()
        param_lr = getattr(param, "optimize_attr",
                           {"learning_rate": 1.0}).get("learning_rate", 1.0)
        if param_lr == 1.0:
            return lr
        from .layers import scale as scale_layer
        return scale_layer(lr, scale=float(param_lr))

    # ---- accumulators -----------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        key = (name, param.name)
        if key in self._accumulators:
            return self._accumulators[key]
        helper = LayerHelper(name)
        shape = list(shape if shape is not None else param.shape)
        var = helper.create_global_variable(
            name=unique_name.generate("%s_%s" % (param.name, name)),
            dtype=dtype or "float32", shape=tuple(shape), persistable=True)
        # moments follow the param's sharding so optimizer state is
        # distributed with the weights (ZeRO-like by construction)
        var.sharding = param.sharding if shape == list(param.shape) else None
        helper.set_variable_initializer(var,
                                        ConstantInitializer(fill_value))
        self._accumulators[key] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[(name, param.name)]

    # ---- hooks ------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # ---- main entry points ------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set, callbacks)

    def apply_gradients(self, params_grads):
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        if self._grad_clip is not None:
            params_grads = self._grad_clip._process(params_grads)
        else:
            params_grads = clip_mod.append_gradient_clip_ops(params_grads)
        block = default_main_program().global_block()
        self._create_global_learning_rate()
        self._create_accumulators(block,
                                  [p for p, g in params_grads
                                   if getattr(p, "trainable", True)])
        for param_and_grad in params_grads:
            if param_and_grad[1] is None:
                continue
            self._append_optimize_op(block, param_and_grad)
        self._finish_update(block, params_grads)
        return []

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        if grad_clip is not None:
            self._grad_clip = grad_clip
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        lr = self._create_param_lr(param)
        block.append_op(
            "sgd",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name]},
            attrs={"op_role": "optimize"})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super(MomentumOptimizer, self).__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        lr = self._create_param_lr(param)
        block.append_op(
            "momentum",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Velocity": [velocity.name], "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name],
                     "VelocityOut": [velocity.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov,
                   "op_role": "optimize"})


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super(LarsMomentumOptimizer, self).__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        lr = self._create_param_lr(param)
        block.append_op(
            "lars_momentum",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Velocity": [velocity.name], "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name],
                     "VelocityOut": [velocity.name]},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay,
                   "op_role": "optimize"})


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6,
                 initial_accumulator_value=0.0, **kw):
        super(AdagradOptimizer, self).__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        lr = self._create_param_lr(param)
        block.append_op(
            "adagrad",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Moment": [moment.name], "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "MomentOut": [moment.name]},
            attrs={"epsilon": self._epsilon, "op_role": "optimize"})


class AdadeltaOptimizer(Optimizer):
    """Adadelta (ref fluid optimizer.py AdadeltaOptimizer /
    adadelta_op.cc): rho-decayed accumulators of squared gradients and
    squared updates; learning_rate is accepted for API parity but the
    classic update is scale-free."""

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, **kw):
        super(AdadeltaOptimizer, self).__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        eg = self._get_accumulator("avg_squared_grad", param)
        ex = self._get_accumulator("avg_squared_update", param)
        block.append_op(
            "adadelta",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "AvgSquaredGrad": [eg.name],
                    "AvgSquaredUpdate": [ex.name]},
            outputs={"ParamOut": [param.name],
                     "AvgSquaredGradOut": [eg.name],
                     "AvgSquaredUpdateOut": [ex.name]},
            attrs={"epsilon": self._epsilon, "rho": self._rho,
                   "op_role": "optimize"})


class DGCMomentumOptimizer(MomentumOptimizer):
    """API-parity Momentum (ref optimizer.py DGCMomentumOptimizer).

    The reference adds Deep Gradient Compression — top-k sparsified
    allreduce to survive commodity-network bandwidth. Over ICI a dense
    XLA allreduce is faster than compression + sparsity bookkeeping, so
    this runs EXACT (uncompressed) momentum: strictly more accurate
    than DGC, same optimizer semantics. Compression knobs are accepted
    and recorded but intentionally unused."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None, **kw):
        super(DGCMomentumOptimizer, self).__init__(
            learning_rate, momentum, use_nesterov=use_nesterov, **kw)
        self._dgc_ignored = {"rampup_begin_step": rampup_begin_step,
                             "rampup_step": rampup_step,
                             "sparsity": tuple(sparsity)}


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super(DecayedAdagradOptimizer, self).__init__(learning_rate, **kw)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        lr = self._create_param_lr(param)
        block.append_op(
            "decayed_adagrad",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Moment": [moment.name], "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "MomentOut": [moment.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon,
                   "op_role": "optimize"})


class _AdamLike(Optimizer):
    _update_op = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super(_AdamLike, self).__init__(learning_rate, **kw)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                  fill_value=self._beta1)
            self._add_accumulator("beta2_pow_acc", p, shape=[1],
                                  fill_value=self._beta2)

    def _extra_attrs(self):
        return {}

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        lr = self._create_param_lr(param)
        # reference lazy mode applies only to SelectedRows grads, i.e.
        # embedding tables — not dense weights that happen to have a
        # zero-grad row this step (dead ReLU etc.)
        lazy = self._lazy_mode and any(
            op.type in ("lookup_table", "lookup_table_v2") and
            param.name in op.input("W") for op in block.ops)
        attrs = {"beta1": self._beta1, "beta2": self._beta2,
                 "epsilon": self._epsilon, "op_role": "optimize",
                 "lazy_mode": lazy}
        attrs.update(self._extra_attrs())
        block.append_op(
            self._update_op,
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Moment1": [m1.name], "Moment2": [m2.name],
                    "Beta1Pow": [b1p.name], "Beta2Pow": [b2p.name],
                    "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "Moment1Out": [m1.name],
                     "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
                     "Beta2PowOut": [b2p.name]},
            attrs=attrs)


class AdamOptimizer(_AdamLike):
    _update_op = "adam"


class AdamWOptimizer(_AdamLike):
    _update_op = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super(AdamWOptimizer, self).__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _extra_attrs(self):
        return {"coeff": self._coeff}


class LambOptimizer(_AdamLike):
    _update_op = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kw):
        super(LambOptimizer, self).__init__(learning_rate, beta1, beta2,
                                            epsilon, **kw)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        param = param_and_grad[0]
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(param):
            wd = 0.0
        self._wd_current = wd
        super(LambOptimizer, self)._append_optimize_op(block, param_and_grad)

    def _extra_attrs(self):
        return {"weight_decay": getattr(self, "_wd_current",
                                        self._weight_decay)}


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super(AdamaxOptimizer, self).__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, shape=[1],
                                  fill_value=self._beta1)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        inf_norm = self._get_accumulator("inf_norm", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        lr = self._create_param_lr(param)
        block.append_op(
            "adamax",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "Moment": [moment.name], "InfNorm": [inf_norm.name],
                    "Beta1Pow": [b1p.name], "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "MomentOut": [moment.name],
                     "InfNormOut": [inf_norm.name]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "op_role": "optimize"})
        # beta1_pow update
        block.append_op("scale", inputs={"X": [b1p.name]},
                        outputs={"Out": [b1p.name]},
                        attrs={"scale": self._beta1, "op_role": "optimize"})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super(RMSPropOptimizer, self).__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("mean_square", p)
            self._add_accumulator("momentum", p)
            if self._centered:
                self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        ms = self._get_accumulator("mean_square", param)
        mom = self._get_accumulator("momentum", param)
        lr = self._create_param_lr(param)
        inputs = {"Param": [param.name], "Grad": [grad.name],
                  "MeanSquare": [ms.name], "Moment": [mom.name],
                  "LearningRate": [lr.name]}
        outputs = {"ParamOut": [param.name], "MeanSquareOut": [ms.name],
                   "MomentOut": [mom.name]}
        if self._centered:
            mg = self._get_accumulator("mean_grad", param)
            inputs["MeanGrad"] = [mg.name]
            outputs["MeanGradOut"] = [mg.name]
        block.append_op(
            "rmsprop", inputs=inputs, outputs=outputs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered,
                   "op_role": "optimize"})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super(FtrlOptimizer, self).__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        lr = self._create_param_lr(param)
        block.append_op(
            "ftrl",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "SquaredAccumulator": [sq.name],
                    "LinearAccumulator": [lin.name],
                    "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name], "SquaredAccumOut": [sq.name],
                     "LinearAccumOut": [lin.name]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power, "op_role": "optimize"})


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate, clip=10.0, batch_size=16.0,
                 sigma=1.0, **kw):
        super(DpsgdOptimizer, self).__init__(learning_rate, **kw)
        self._clip, self._sigma = clip, sigma

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        lr = self._create_param_lr(param)
        block.append_op(
            "dpsgd",
            inputs={"Param": [param.name], "Grad": [grad.name],
                    "LearningRate": [lr.name]},
            outputs={"ParamOut": [param.name]},
            attrs={"clip": self._clip, "sigma": self._sigma,
                   "op_role": "optimize"})


class ExponentialMovingAverage(object):
    """EMA of parameters (reference optimizer.py ExponentialMovingAverage).
    update() appends in-graph EMA ops; apply()/restore() swap params."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._ema_vars = {}

    def update(self):
        program = default_main_program()
        block = program.global_block()
        helper = LayerHelper(self._name)
        for param in program.all_parameters():
            if not getattr(param, "trainable", True):
                continue
            ema = helper.create_global_variable(
                name=unique_name.generate(param.name + ".ema"),
                dtype=param.dtype, shape=param.shape, persistable=True)
            helper.set_variable_initializer(ema, ConstantInitializer(0.0))
            self._ema_vars[param.name] = ema
            tmp1 = helper.create_variable_for_type_inference(param.dtype,
                                                             param.shape)
            block.append_op("scale", inputs={"X": [ema.name]},
                            outputs={"Out": [tmp1.name]},
                            attrs={"scale": self._decay,
                                   "op_role": "optimize"})
            tmp2 = helper.create_variable_for_type_inference(param.dtype,
                                                             param.shape)
            block.append_op("scale", inputs={"X": [param.name]},
                            outputs={"Out": [tmp2.name]},
                            attrs={"scale": 1.0 - self._decay,
                                   "op_role": "optimize"})
            block.append_op("sum", inputs={"X": [tmp1.name, tmp2.name]},
                            outputs={"Out": [ema.name]},
                            attrs={"op_role": "optimize"})

    def apply(self, executor, need_restore=True):
        """Swap params with their EMA values in the scope."""
        from .framework.scope import global_scope
        import numpy as np
        scope = global_scope()
        self._backup = {}
        for pname, ema in self._ema_vars.items():
            pv = scope.find_var(pname)
            ev = scope.find_var(ema.name)
            if pv is None or ev is None:
                continue
            self._backup[pname] = pv
            scope.set_var(pname, ev)
        import contextlib

        @contextlib.contextmanager
        def guard():
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)
        return guard()

    def restore(self, executor=None):
        from .framework.scope import global_scope
        scope = global_scope()
        for pname, val in getattr(self, "_backup", {}).items():
            scope.set_var(pname, val)
        self._backup = {}


class LookaheadOptimizer(object):
    """Reference optimizer.py LookaheadOptimizer: wraps a fast optimizer,
    every k steps slow weights interpolate toward fast weights. The k-step
    branch runs on device via a where-select on a step counter."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        ops, pgs = self.inner_optimizer.minimize(loss, startup_program)
        program = default_main_program()
        block = program.global_block()
        helper = LayerHelper("lookahead")
        from . import layers as L
        step = L.autoincreased_step_counter(
            counter_name="@LOOKAHEAD_STEP@", begin=1)
        stepf = L.cast(step, "float32")
        k = L.fill_constant([1], "float32", float(self.k))
        rem = L.elementwise_sub(
            stepf, L.elementwise_mul(L.floor(L.elementwise_div(stepf, k)), k))
        is_sync = L.equal(rem, 0.0)
        for param, _ in pgs:
            slow = helper.create_global_variable(
                name=unique_name.generate(param.name + ".slow"),
                dtype=param.dtype, shape=param.shape, persistable=True)
            helper.set_variable_initializer(slow, ConstantInitializer(0.0))
            mixed = helper.create_variable_for_type_inference(param.dtype,
                                                              param.shape)
            t1 = helper.create_variable_for_type_inference(param.dtype,
                                                           param.shape)
            block.append_op("scale", inputs={"X": [param.name]},
                            outputs={"Out": [t1.name]},
                            attrs={"scale": self.alpha,
                                   "op_role": "optimize"})
            t2 = helper.create_variable_for_type_inference(param.dtype,
                                                           param.shape)
            block.append_op("scale", inputs={"X": [slow.name]},
                            outputs={"Out": [t2.name]},
                            attrs={"scale": 1.0 - self.alpha,
                                   "op_role": "optimize"})
            block.append_op("sum", inputs={"X": [t1.name, t2.name]},
                            outputs={"Out": [mixed.name]},
                            attrs={"op_role": "optimize"})
            new_p = L.where(is_sync, mixed, param)
            new_slow = L.where(is_sync, mixed, slow)
            block.append_op("assign", inputs={"X": [new_p.name]},
                            outputs={"Out": [param.name]},
                            attrs={"op_role": "optimize"})
            block.append_op("assign", inputs={"X": [new_slow.name]},
                            outputs={"Out": [slow.name]},
                            attrs={"op_role": "optimize"})
        return ops, pgs


class ModelAverage(object):
    """Sliding-window parameter averaging.

    Reference parity: python/paddle/fluid/optimizer.py:2721 (class
    ModelAverage) + operators/average_accumulates_op.h. The accumulation op
    is appended in-graph after the optimize ops, so it fuses into the same
    jitted step (no per-step host work). ``apply()`` swaps scope params with
    (sum_1+sum_2+sum_3)/(num_accumulates+old_num_accumulates);
    ``restore()`` puts the trained params back.
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        self._rate = float(average_window_rate)
        self._min_w = int(min_average_window)
        self._max_w = int(max_average_window)
        self._name = name or "model_average"
        self._accs = {}  # param name -> {slot: Variable}
        program = default_main_program()
        block = program.global_block()
        helper = LayerHelper(self._name)
        for param in program.all_parameters():
            if not getattr(param, "trainable", True):
                continue
            accs = {}
            for slot in ("sum_1", "sum_2", "sum_3"):
                v = helper.create_global_variable(
                    name=unique_name.generate(param.name + "." + slot),
                    dtype=param.dtype, shape=param.shape, persistable=True)
                helper.set_variable_initializer(v, ConstantInitializer(0.0))
                accs[slot] = v
            for slot in ("num_accumulates", "old_num_accumulates",
                         "num_updates"):
                v = helper.create_global_variable(
                    name=unique_name.generate(param.name + "." + slot),
                    dtype="int32", shape=[1], persistable=True)
                helper.set_variable_initializer(v, ConstantInitializer(0))
                accs[slot] = v
            self._accs[param.name] = accs
            block.append_op(
                "average_accumulates",
                inputs={"param": [param.name],
                        "in_sum_1": [accs["sum_1"].name],
                        "in_sum_2": [accs["sum_2"].name],
                        "in_sum_3": [accs["sum_3"].name],
                        "in_num_accumulates": [accs["num_accumulates"].name],
                        "in_old_num_accumulates":
                            [accs["old_num_accumulates"].name],
                        "in_num_updates": [accs["num_updates"].name]},
                outputs={"out_sum_1": [accs["sum_1"].name],
                         "out_sum_2": [accs["sum_2"].name],
                         "out_sum_3": [accs["sum_3"].name],
                         "out_num_accumulates":
                             [accs["num_accumulates"].name],
                         "out_old_num_accumulates":
                             [accs["old_num_accumulates"].name],
                         "out_num_updates": [accs["num_updates"].name]},
                attrs={"average_window": self._rate,
                       "min_average_window": self._min_w,
                       "max_average_window": self._max_w,
                       "op_role": "optimize"})

    def apply(self, executor=None, need_restore=True):
        """Swap each param with its current window average (context
        manager, mirroring the reference apply())."""
        import contextlib
        import jax.numpy as jnp
        from .framework.scope import global_scope
        scope = global_scope()
        self._backup = {}
        for pname, accs in self._accs.items():
            pv = scope.find_var(pname)
            s1 = scope.find_var(accs["sum_1"].name)
            s2 = scope.find_var(accs["sum_2"].name)
            s3 = scope.find_var(accs["sum_3"].name)
            na = scope.find_var(accs["num_accumulates"].name)
            no = scope.find_var(accs["old_num_accumulates"].name)
            if pv is None or s1 is None or na is None:
                continue
            total = jnp.maximum((na + no).astype(jnp.float32), 1.0)
            avg = ((s1.astype(jnp.float32) + s2 + s3) /
                   total.reshape(())).astype(pv.dtype)
            self._backup[pname] = pv
            scope.set_var(pname, avg)

        @contextlib.contextmanager
        def guard():
            try:
                yield
            finally:
                if need_restore:
                    self.restore(executor)
        return guard()

    def restore(self, executor=None):
        from .framework.scope import global_scope
        scope = global_scope()
        for pname, val in getattr(self, "_backup", {}).items():
            scope.set_var(pname, val)
        self._backup = {}


class RecomputeOptimizer(object):
    """Reference RecomputeOptimizer trades memory for compute by re-running
    checkpointed segments in backward. On TPU the equivalent lever is XLA
    rematerialization: our grad ops already recompute via vjp when the
    executor marks segments (see SURVEY §2.5); this wrapper keeps API parity
    and records checkpoint vars for the build strategy."""

    def __init__(self, optimizer):
        self.inner_optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        program._recompute_checkpoints = [
            v.name if hasattr(v, "name") else v
            for v in (self._checkpoints or [])]
        return self.inner_optimizer.minimize(loss, startup_program,
                                             parameter_list, no_grad_set)


# fluid-style aliases
from .contrib.extend_optimizer import PipelineOptimizer  # noqa: E402,F401
Adadelta = AdadeltaOptimizer
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
Dpsgd = DpsgdOptimizer
