"""``paddle.fluid`` alias package.

Reference scripts spell imports ``import paddle.fluid as fluid`` /
``from paddle.fluid.layers import nn``; this framework's modules live at
``paddle_tpu.X``. A meta-path finder (registered first, so the normal
path machinery never double-loads anything) resolves every
``paddle_tpu.fluid.X`` to a lightweight PROXY module whose attribute
access forwards to the already-imported ``paddle_tpu.X`` — one copy of
all module state, and ported fluid scripts only rewrite the root
package name. Attribute access on ``paddle_tpu.fluid`` itself proxies
the top-level package the same way.
"""
import importlib
import importlib.abc
import importlib.util
import sys
import types

import paddle_tpu as _pt

_PREFIX = __name__ + "."


def __getattr__(name):
    return getattr(_pt, name)


def __dir__():
    return sorted(set(dir(_pt)) | set(globals()))


def _is_importable(name):
    if name in sys.modules:
        return True
    try:
        return importlib.util.find_spec(name) is not None
    except (ImportError, ValueError):
        return False


class _AliasLoader(importlib.abc.Loader):
    def __init__(self, real_name):
        self._real_name = real_name

    def create_module(self, spec):
        real = importlib.import_module(self._real_name)
        proxy = types.ModuleType(spec.name, real.__doc__)
        proxy.__getattr__ = lambda name, _r=real: getattr(_r, name)
        proxy.__dir__ = lambda _r=real: dir(_r)
        return proxy

    def exec_module(self, module):
        pass


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith(_PREFIX):
            return None
        real = "paddle_tpu." + fullname[len(_PREFIX):]
        if not _is_importable(real):
            return None
        spec = importlib.util.spec_from_loader(fullname,
                                               _AliasLoader(real))
        # every alias is marked package-like with an EMPTY search path:
        # descendants must come back through this finder (a real path
        # here would let PathFinder double-load the underlying files)
        spec.submodule_search_locations = []
        return spec


if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.insert(0, _AliasFinder())
