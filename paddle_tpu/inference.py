"""Inference engine: load → compiled predictor.

Reference parity: paddle/fluid/inference/ (AnalysisConfig/AnalysisPredictor,
api_impl.cc). The reference runs analysis passes + TensorRT/Anakin engines;
on TPU the engine IS XLA: create_predictor returns a callable whose whole
pruned inference program is one jitted computation, with a compile cache
bucketed by padded batch size so ragged request sizes don't retrigger
compilation (reference: dynamic-shape TRT profiles).
"""
import math

import numpy as np

from .framework.executor import Executor
from .framework.scope import Scope, scope_guard
from .framework.place import _current_expected_place
from .io import load_inference_model


class Config(object):
    """AnalysisConfig work-alike."""

    def __init__(self, model_dir):
        self.model_dir = model_dir
        self.batch_buckets = (1, 2, 4, 8, 16, 32, 64)
        self.place = None
        # {feed_name: batch_factor} — needed only when NO dynamic feed
        # carries dim0 == batch (see serving.infer_batch_factors)
        self.feed_batch_factors = None

    def enable_memory_optim(self):
        pass  # XLA plans buffers itself; parity no-op

    def switch_ir_optim(self, flag=True):
        pass


class Predictor(object):
    def __init__(self, config):
        self._scope = Scope()
        self._exe = Executor(config.place or _current_expected_place())
        with scope_guard(self._scope):
            self._program, self._feed_names, self._fetch_names = \
                load_inference_model(config.model_dir, self._exe)
        self._buckets = sorted(config.batch_buckets)
        self._factor_overrides = dict(
            getattr(config, "feed_batch_factors", None) or {})
        # static per program: which feeds/fetches are declared
        # batch-dynamic (leading -1)
        blk = self._program.global_block()

        def _dyn(name):
            var = blk._find_var_recursive(name)
            shape = list(var.shape) if var is not None and \
                var.shape is not None else [-1]
            return bool(shape) and shape[0] == -1

        self._dyn_feeds = {n: _dyn(n) for n in self._feed_names}
        self._dyn_fetches = [_dyn(n) for n in self._fetch_names]

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def _bucket(self, n):
        for b in self._buckets:
            if n <= b:
                return b
        return int(2 ** math.ceil(math.log2(max(n, 1))))

    def run(self, inputs):
        """inputs: dict name -> np array (or list aligned with feed names).
        Returns list of np arrays aligned with fetch names. Batches are
        padded up to the bucket size and results sliced back; feeds whose
        leading dim is a multiple of the batch (BERT's flat mask_pos =
        batch * max_preds) pad to bucket * factor — same contract as the
        v2 serving artifact (Config.feed_batch_factors overrides the
        inference when no feed carries dim0 == batch)."""
        from .serving import infer_batch_factors
        if isinstance(inputs, (list, tuple)):
            inputs = dict(zip(self._feed_names, inputs))
        dyn_dims = [(name, np.asarray(inputs[name]).shape[0])
                    for name in self._feed_names
                    if self._dyn_feeds[name]]
        factors, n = infer_batch_factors(dyn_dims,
                                         self._factor_overrides)
        if n is None:   # fully static program: run as-is
            with scope_guard(self._scope):
                return self._exe.run(self._program, feed=dict(inputs),
                                     fetch_list=self._fetch_names)
        b = self._bucket(max(n, 1))
        feed = {}
        for name, arr in inputs.items():
            arr = np.asarray(arr)
            f = factors.get(name, 0)
            if f and arr.shape[0] != b * f:
                pad = [(0, b * f - arr.shape[0])] + \
                    [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad)
            feed[name] = arr
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_names)
        # slice ONLY fetches declared batch-dynamic in the program — a
        # static output dim that happens to equal bucket*factor is never
        # truncated
        out_factors = sorted({f for f in factors.values() if f},
                             reverse=True)
        sliced = []
        for o, dyn in zip(outs, self._dyn_fetches):
            if dyn and hasattr(o, "__getitem__") and np.ndim(o) > 0:
                for f in out_factors:
                    if o.shape[0] == b * f:
                        o = o[:n * f]
                        break
            sliced.append(o)
        return sliced


def create_predictor(config):
    return Predictor(config)


# legacy-style API (reference paddle/fluid/inference/api)
create_paddle_predictor = create_predictor
AnalysisConfig = Config
