"""Inference engine: load → compiled predictor.

Reference parity: paddle/fluid/inference/ (AnalysisConfig/AnalysisPredictor,
api_impl.cc). The reference runs analysis passes + TensorRT/Anakin engines;
on TPU the engine IS XLA: create_predictor returns a callable whose whole
pruned inference program is one jitted computation, with a compile cache
bucketed by padded batch size so ragged request sizes don't retrigger
compilation (reference: dynamic-shape TRT profiles).
"""
import math

import numpy as np

from .framework.executor import Executor
from .framework.scope import Scope, scope_guard
from .framework.place import _current_expected_place
from .io import load_inference_model


class Config(object):
    """AnalysisConfig work-alike."""

    def __init__(self, model_dir):
        self.model_dir = model_dir
        self.batch_buckets = (1, 2, 4, 8, 16, 32, 64)
        self.place = None

    def enable_memory_optim(self):
        pass  # XLA plans buffers itself; parity no-op

    def switch_ir_optim(self, flag=True):
        pass


class Predictor(object):
    def __init__(self, config):
        self._scope = Scope()
        self._exe = Executor(config.place or _current_expected_place())
        with scope_guard(self._scope):
            self._program, self._feed_names, self._fetch_names = \
                load_inference_model(config.model_dir, self._exe)
        self._buckets = sorted(config.batch_buckets)

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def _bucket(self, n):
        for b in self._buckets:
            if n <= b:
                return b
        return int(2 ** math.ceil(math.log2(max(n, 1))))

    def run(self, inputs):
        """inputs: dict name -> np array (or list aligned with feed names).
        Returns list of np arrays aligned with fetch names. Batches are
        padded up to the bucket size and results sliced back."""
        if isinstance(inputs, (list, tuple)):
            inputs = dict(zip(self._feed_names, inputs))
        n = next(iter(inputs.values())).shape[0]
        b = self._bucket(n)
        feed = {}
        for name, arr in inputs.items():
            arr = np.asarray(arr)
            if arr.shape[0] != b:
                pad = [(0, b - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad)
            feed[name] = arr
        with scope_guard(self._scope):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_names)
        return [o[:n] if hasattr(o, "__getitem__") and
                np.ndim(o) > 0 and o.shape[0] == b else o for o in outs]


def create_predictor(config):
    return Predictor(config)


# legacy-style API (reference paddle/fluid/inference/api)
create_paddle_predictor = create_predictor
AnalysisConfig = Config
