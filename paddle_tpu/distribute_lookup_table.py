"""fluid.distribute_lookup_table parity (ref
python/paddle/fluid/distribute_lookup_table.py): locate the distributed
embedding table a program uses (is_distributed lookup_table ops)."""

LOOKUP_TABLE_TYPE = "lookup_table"

__all__ = ["find_distributed_lookup_table",
           "find_distributed_lookup_table_inputs",
           "find_distributed_lookup_table_outputs"]


def find_distributed_lookup_table(program):
    table_name = None
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and \
                op.attr("is_distributed") is True:
            w = op.input("W")[0]
            if table_name is None:
                table_name = w
            elif table_name != w:
                raise RuntimeError("all distributed lookup_table_ops "
                                   "should have only one table")
        elif op.type == LOOKUP_TABLE_TYPE:
            if table_name == (op.input("W") or [None])[0]:
                raise RuntimeError("lookup_table_ops on the same table "
                                   "must all be distributed")
    return table_name


def find_distributed_lookup_table_inputs(program, table_name):
    ins = []
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and \
                table_name == op.input("W")[0]:
            ins.extend(op.input("Ids"))
    return ins


def find_distributed_lookup_table_outputs(program, table_name):
    outs = []
    for op in program.global_block().ops:
        if op.type == LOOKUP_TABLE_TYPE and \
                table_name == op.input("W")[0]:
            outs.extend(op.output("Out"))
    return outs
