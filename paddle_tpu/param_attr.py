"""ParamAttr — parameter configuration.

Reference parity: python/paddle/fluid/param_attr.py.
Adds a TPU-native ``sharding`` field: a PartitionSpec-like tuple mapping each
parameter dim to a mesh axis (or None), consumed by CompiledProgram/pjit.
"""
from . import initializer as init_mod


class ParamAttr(object):
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=False, sharding=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        self.sharding = tuple(sharding) if sharding is not None else None

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, init_mod.Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        if isinstance(arg, (int, float)):
            return ParamAttr(learning_rate=float(arg))
        raise TypeError("cannot make ParamAttr from %r" % (arg,))

    def _to_kwargs(self, with_initializer=False):
        kwargs = {
            "name": self.name,
            "optimize_attr": {"learning_rate": self.learning_rate},
            "regularizer": self.regularizer,
            "trainable": self.trainable,
            "gradient_clip_attr": self.gradient_clip,
            "do_model_average": self.do_model_average,
            "sharding": self.sharding,
        }
        if with_initializer:
            kwargs["initializer"] = self.initializer
        return kwargs


WeightNormParamAttr = ParamAttr  # weight-norm reparam tracked in SURVEY §2
