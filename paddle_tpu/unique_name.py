"""Module-path alias for fluid.unique_name (ref
python/paddle/fluid/unique_name.py); implementation lives in
framework/unique_name.py."""
from .framework.unique_name import *  # noqa: F401,F403
from .framework import unique_name as _un

__all__ = list(getattr(_un, "__all__", []))
