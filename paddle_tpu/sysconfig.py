"""paddle.sysconfig parity (ref python/paddle/sysconfig.py)."""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory of this package's headers/sources (the reference points
    at its C++ headers; the native data plane's sources live here)."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "native")


def get_lib():
    """Directory containing the built native libraries: the dataplane
    .so lands in the build cache (native/build.py _cache_dir), not the
    source tree."""
    from .native.build import _cache_dir
    return _cache_dir()
