"""Collective/step timeout watchdog — halt & failure detection.

Reference parity: the reference's collective ops carry a timeout and the
trainer aborts on stuck NCCL rings (operators/collective/ +
check_nan_inf-style failure hooks). Under XLA a hung ICI/DCN collective
(straggler host, preempted chip) shows up as a step whose outputs never
become ready, so the TPU-native guard is a watchdog around
``block_until_ready``: the wait runs on a helper thread and a bounded join
turns a silent hang into a diagnosable CollectiveTimeoutError.
"""
import threading

import jax

__all__ = ["CollectiveTimeoutError", "wait_with_timeout", "bounded_call"]


class CollectiveTimeoutError(RuntimeError):
    """A jitted step (and therefore some collective in it) failed to
    complete within the configured timeout."""


def bounded_call(fn, timeout_s, name="paddle_tpu-bounded-call"):
    """Run ``fn()`` on a daemon helper thread with a bounded join.

    Returns ``(done, value, error)``; ``done`` False means the join
    timed out and the orphaned thread keeps running in the background.
    The one detect-the-hang mechanism shared by wait_with_timeout and
    resilience.run_with_deadline."""
    box = {}
    done = threading.Event()

    def _worker():
        try:
            box["value"] = fn()
        except BaseException as e:      # surface errors to the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_worker, daemon=True, name=name)
    t.start()
    if not done.wait(float(timeout_s)):
        return False, None, None
    return True, box.get("value"), box.get("error")


def wait_with_timeout(outputs, timeout_s, what="jitted step"):
    """Block until every array in ``outputs`` is ready, or raise
    CollectiveTimeoutError after ``timeout_s`` seconds.

    The computation itself cannot be cancelled (XLA owns the device), but
    raising lets the trainer log, checkpoint-abort, or tear down the mesh
    instead of hanging forever — the reference's collective-timeout
    semantics. Returns ``outputs`` for call-through style.
    """
    if timeout_s is None:
        return outputs
    leaves = jax.tree_util.tree_leaves(outputs)

    def _wait_all():
        for leaf in leaves:
            ready = getattr(leaf, "block_until_ready", None)
            if ready is not None:
                ready()

    done, _, err = bounded_call(_wait_all, timeout_s,
                                name="paddle_tpu-collective-watchdog")
    if not done:
        # observability: every watchdog trip lands in the resilience
        # event log (lazy import — resilience imports this module)
        from . import resilience
        resilience.record_event("watchdog_timeout", what=what,
                                timeout_s=float(timeout_s))
        raise CollectiveTimeoutError(
            "%s did not complete within %.1fs (process %d/%d, %d local "
            "devices) — likely a hung collective: straggler or failed "
            "host, or a mismatched mesh/sharding across processes"
            % (what, float(timeout_s), jax.process_index(),
               jax.process_count(), jax.local_device_count()))
    if err is not None:
        raise err
    return outputs
