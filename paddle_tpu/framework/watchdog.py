"""Collective/step timeout watchdog — halt & failure detection.

Reference parity: the reference's collective ops carry a timeout and the
trainer aborts on stuck NCCL rings (operators/collective/ +
check_nan_inf-style failure hooks). Under XLA a hung ICI/DCN collective
(straggler host, preempted chip) shows up as a step whose outputs never
become ready, so the TPU-native guard is a watchdog around
``block_until_ready``: the wait runs on a helper thread and a bounded join
turns a silent hang into a diagnosable CollectiveTimeoutError.
"""
import threading

import jax

__all__ = ["CollectiveTimeoutError", "wait_with_timeout", "bounded_call",
           "StragglerDetector", "enable_straggler_detection",
           "disable_straggler_detection", "straggler_detector",
           "observe_step_latency", "straggler_action_due"]


class CollectiveTimeoutError(RuntimeError):
    """A jitted step (and therefore some collective in it) failed to
    complete within the configured timeout."""


class StragglerDetector(object):
    """Per-step latency EWMA — flag a slow host BEFORE it hangs.

    The watchdog only knows "done within timeout_s"; a straggling host
    (thermal throttle, noisy neighbor, degrading ICI link) serves k
    warnings before it becomes a hard CollectiveTimeoutError. Each
    ``observe(seconds)`` updates ``ewma = alpha*x + (1-alpha)*ewma`` and
    records a ``straggler`` resilience event when a step exceeds
    ``k × ewma`` (after ``warmup`` samples, and only past
    ``min_latency_s`` so microsecond jitter never pages anyone).

    Straggler samples still update the EWMA: a PERSISTENT slowdown
    recalibrates the baseline instead of flagging every step forever —
    the signal is the transition, which is when rebalancing helps.

    MITIGATION, not just detection: ``action_k`` (> k) arms a second,
    critical threshold. A step past ``action_k × ewma`` is a host that
    is very probably about to become a hard CollectiveTimeoutError, so
    the detector latches an action flag (``straggler_critical`` event);
    the training loop polls :func:`straggler_action_due` at the next
    step boundary and takes a pre-emptive checkpoint (``straggler_ckpt``
    event) — the eventual hang then costs at most one step of replay.
    """

    def __init__(self, alpha=0.2, k=3.0, warmup=5, min_latency_s=0.0,
                 action_k=None):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        if k <= 1.0:
            raise ValueError("k must be > 1 (k*ewma is the flag line)")
        if action_k is not None and action_k < k:
            raise ValueError("action_k is the SECOND threshold — it must "
                             "be >= k (got action_k=%g < k=%g)"
                             % (action_k, k))
        self.alpha = float(alpha)
        self.k = float(k)
        self.warmup = int(warmup)
        self.min_latency_s = float(min_latency_s)
        self.action_k = None if action_k is None else float(action_k)
        self._action_due = False
        self._ewma = None
        self._n = 0
        self._lock = threading.Lock()

    @property
    def ewma_s(self):
        return self._ewma

    @property
    def count(self):
        return self._n

    def observe(self, seconds, what="step"):
        """Feed one step latency; True if it was flagged as a straggler."""
        seconds = float(seconds)
        with self._lock:
            # ewma > 0: a zero baseline has no meaningful ratio (and
            # would flag every positive sample forever)
            flagged = (self._n >= self.warmup and self._ewma is not None
                       and self._ewma > 0.0
                       and seconds > self.k * self._ewma
                       and seconds > self.min_latency_s)
            critical = (flagged and self.action_k is not None
                        and seconds > self.action_k * self._ewma)
            if critical:
                self._action_due = True
            ewma = self._ewma
            self._ewma = seconds if self._ewma is None else (
                self.alpha * seconds + (1.0 - self.alpha) * self._ewma)
            self._n += 1
        if flagged:
            from . import resilience
            resilience.record_event("straggler", what=what,
                                    latency_s=seconds, ewma_s=ewma,
                                    ratio=seconds / ewma)
        if critical:
            from . import resilience
            resilience.record_event("straggler_critical", what=what,
                                    latency_s=seconds, ewma_s=ewma,
                                    ratio=seconds / ewma)
        return flagged

    def action_due(self):
        """Consume the latched critical flag: True once per critical
        straggler, then False until the next one. The trainer that polls
        this takes the pre-emptive checkpoint."""
        with self._lock:
            due = self._action_due
            self._action_due = False
            return due


# opt-in global detector: armed by ResilientTrainer/operators that want
# early warning; a no-op by default so unrelated runs never pay for it
_detector = [None]


def enable_straggler_detection(alpha=0.2, k=3.0, warmup=5,
                               min_latency_s=0.0, action_k=None):
    """Install (and return) the process-global StragglerDetector fed by
    Executor.run/run_steps and armed wait_with_timeout calls.
    ``action_k`` arms the second (mitigation) threshold — see
    StragglerDetector."""
    _detector[0] = StragglerDetector(alpha=alpha, k=k, warmup=warmup,
                                     min_latency_s=min_latency_s,
                                     action_k=action_k)
    return _detector[0]


def disable_straggler_detection():
    _detector[0] = None


def straggler_detector():
    return _detector[0]


def observe_step_latency(seconds, what="step"):
    """Feed the global detector (no-op when detection is disabled)."""
    det = _detector[0]
    if det is None:
        return False
    return det.observe(seconds, what=what)


def straggler_action_due():
    """Consume the global detector's critical-straggler flag (False when
    detection is disabled or no critical straggler was seen). Trainers
    poll this at step boundaries to take the pre-emptive checkpoint."""
    det = _detector[0]
    if det is None:
        return False
    return det.action_due()


def bounded_call(fn, timeout_s, name="paddle_tpu-bounded-call"):
    """Run ``fn()`` on a daemon helper thread with a bounded join.

    Returns ``(done, value, error)``; ``done`` False means the join
    timed out and the orphaned thread keeps running in the background.
    The one detect-the-hang mechanism shared by wait_with_timeout and
    resilience.run_with_deadline."""
    box = {}
    done = threading.Event()

    def _worker():
        try:
            box["value"] = fn()
        except BaseException as e:      # surface errors to the caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_worker, daemon=True, name=name)
    t.start()
    if not done.wait(float(timeout_s)):
        return False, None, None
    return True, box.get("value"), box.get("error")


def wait_with_timeout(outputs, timeout_s, what="jitted step"):
    """Block until every array in ``outputs`` is ready, or raise
    CollectiveTimeoutError after ``timeout_s`` seconds.

    The computation itself cannot be cancelled (XLA owns the device), but
    raising lets the trainer log, checkpoint-abort, or tear down the mesh
    instead of hanging forever — the reference's collective-timeout
    semantics. Returns ``outputs`` for call-through style.
    """
    if timeout_s is None:
        return outputs
    leaves = jax.tree_util.tree_leaves(outputs)

    def _wait_all():
        for leaf in leaves:
            ready = getattr(leaf, "block_until_ready", None)
            if ready is not None:
                ready()

    done, _, err = bounded_call(_wait_all, timeout_s,
                                name="paddle_tpu-collective-watchdog")
    # NOTE: an armed wait does NOT feed the straggler detector —
    # Executor.run/run_steps already observe the full dispatch latency,
    # and the compiled path's one-behind wait is near-zero when fetches
    # were synced, which would halve the EWMA baseline (double-count).
    if not done:
        # observability: every watchdog trip lands in the resilience
        # event log (lazy import — resilience imports this module)
        from . import resilience
        resilience.record_event("watchdog_timeout", what=what,
                                timeout_s=float(timeout_s))
        raise CollectiveTimeoutError(
            "%s did not complete within %.1fs (process %d/%d, %d local "
            "devices) — likely a hung collective: straggler or failed "
            "host, or a mismatched mesh/sharding across processes"
            % (what, float(timeout_s), jax.process_index(),
               jax.process_count(), jax.local_device_count()))
    if err is not None:
        raise err
    return outputs
