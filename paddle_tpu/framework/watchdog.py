"""Collective/step timeout watchdog — halt & failure detection.

Reference parity: the reference's collective ops carry a timeout and the
trainer aborts on stuck NCCL rings (operators/collective/ +
check_nan_inf-style failure hooks). Under XLA a hung ICI/DCN collective
(straggler host, preempted chip) shows up as a step whose outputs never
become ready, so the TPU-native guard is a watchdog around
``block_until_ready``: the wait runs on a helper thread and a bounded join
turns a silent hang into a diagnosable CollectiveTimeoutError.
"""
import threading

import jax

__all__ = ["CollectiveTimeoutError", "wait_with_timeout"]


class CollectiveTimeoutError(RuntimeError):
    """A jitted step (and therefore some collective in it) failed to
    complete within the configured timeout."""


def wait_with_timeout(outputs, timeout_s, what="jitted step"):
    """Block until every array in ``outputs`` is ready, or raise
    CollectiveTimeoutError after ``timeout_s`` seconds.

    The computation itself cannot be cancelled (XLA owns the device), but
    raising lets the trainer log, checkpoint-abort, or tear down the mesh
    instead of hanging forever — the reference's collective-timeout
    semantics. Returns ``outputs`` for call-through style.
    """
    if timeout_s is None:
        return outputs
    leaves = jax.tree_util.tree_leaves(outputs)
    done = threading.Event()
    errs = []

    def _waiter():
        try:
            for leaf in leaves:
                ready = getattr(leaf, "block_until_ready", None)
                if ready is not None:
                    ready()
        except Exception as e:          # surface device errors to caller
            errs.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_waiter, daemon=True,
                         name="paddle_tpu-collective-watchdog")
    t.start()
    if not done.wait(float(timeout_s)):
        raise CollectiveTimeoutError(
            "%s did not complete within %.1fs (process %d/%d, %d local "
            "devices) — likely a hung collective: straggler or failed "
            "host, or a mismatched mesh/sharding across processes"
            % (what, float(timeout_s), jax.process_index(),
               jax.process_count(), jax.local_device_count()))
    if errs:
        raise errs[0]
    return outputs
