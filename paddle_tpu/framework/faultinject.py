"""Deterministic process-wide failpoint plane.

Every resilience guarantee the repo advertises (elastic rewind, router
HA, rolling deploy, checkpoint scrub, numeric-fault recovery) is only
as good as our ability to *cause* the failure it survives.  The legacy
:mod:`resilience` FaultInjector covers three coarse training points;
this module is the production-wide generalisation: **named failpoint
sites** threaded through transport, io, executor, serving and
coordination, each hit deterministically by ``(site, hit-count, host)``
schedules.

Usage at a site (the call is the site)::

    from . import faultinject
    faultinject.hit("transport.send", host=self.host_id)

``hit`` is free when no schedule is armed: a single module-global bool
test (no lock, no dict lookup, no env read).  When armed it counts the
visit per ``(site, host)`` and applies every matching schedule.

Schedules — programmatic or via ``PADDLE_TPU_FAULTS`` (the same env var
the legacy injector reads; specs whose point contains a ``.`` belong to
this plane, bare legacy points stay with :mod:`resilience`)::

    site:action[=arg][@N | @N+ | ~p][^host]

      action   raise[=ExcName[/message]] | delay[=seconds] | drop
               | corrupt=array_name | flip=array_name
      @N       fire only on the N-th visit of (site, host) (1-based)
      @N+      fire on every visit from the N-th on
      ~p       fire each visit with probability p (seeded, so a given
               PADDLE_TPU_FAULT_SEED replays the same schedule)
      ^host    fire only when the site's host context equals ``host``
               (explicit ``host=`` kwarg, else the ``host`` tag from
               resilience.context())

    default (no @/~): fire on every visit.

Actions:

  ``raise``    raise a typed error — the site's default error class
               (catalogued below) unless ``=ExcName`` picks another;
               ``=ExcName/message`` attaches a message.
  ``delay``    sleep ``arg`` seconds (default 0.05) then pass through.
  ``drop``     return the :data:`DROP` sentinel instead of the payload;
               the site interprets it (a heartbeat loop skips the beat,
               a send tears the connection).
  ``corrupt``  NaN-poison one element of the named array in a dict
               payload (the numeric-fault chaos battery's trigger).
  ``flip``     flip one low bit of one element of the named array
               (an SDC simulation — silently wrong, still finite).

Counters: :func:`hits_total` returns ``{site: fired_count}``, exported
by ``resilience.metrics()`` as ``failpoint_hits_total{site=}`` together
with a ``faultinject_armed`` gauge so ``tools/serving_probe.py
--strict`` can refuse a production scrape with live failpoints.

Site names are a closed catalog (:data:`SITES`): ``hit()`` on an
uncatalogued site raises at hit time when armed, and
``tools/codelint.py`` statically rejects any ``faultinject.hit("...")``
literal not in the catalog — a typo'd site must fail the build, not
silently never fire.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading

__all__ = [
    "SITES", "DROP", "FailSpec", "FaultInjectedError",
    "hit", "armed", "arm", "disarm", "failpoints",
    "hits_total", "reset_counters", "reload_env", "schedules",
]


class FaultInjectedError(RuntimeError):
    """Default typed error for ``raise`` actions at sites without a
    more specific catalogued error class."""


# ---------------------------------------------------------------------------
# site catalog — the single source of truth (codelint-enforced)
# ---------------------------------------------------------------------------

# site -> default exception class for the ``raise`` action.  The class
# is chosen so the SITE'S OWN error handling sees the same type a real
# fault would produce: a torn socket is ConnectionError (transport
# retry/failover path), a torn write is OSError (checkpoint scrub
# path), a poisoned step is FloatingPointError (numeric-policy path).
SITES = {
    # coordination transport: one client->server roundtrip is about to
    # put bytes on the wire
    "transport.send": ConnectionError,
    # one liveness heartbeat is about to be sent (drop = miss the beat
    # and let the lease age toward fencing)
    "coordination.hb": ConnectionError,
    # checkpoint shard payload (.npz member) atomic write
    "io.member_write": OSError,
    # checkpoint manifest/latest atomic write — the commit record
    "io.manifest_write": OSError,
    # one executor step about to run; payload = feeds dict, so
    # ``corrupt``/``flip`` can poison a named input array
    "executor.step": FloatingPointError,
    # elastic pp re-cut about to re-target the survivors' mesh (a
    # raise here exercises the half-completed-re-cut window: the pod
    # must fall back to the consensus rewind, never crash or shrink
    # silently)
    "coordination.recut": RuntimeError,
    # router about to dispatch a coalesced micro-batch to a replica
    "serving.dispatch": OSError,
    # replica about to run one /infer body
    "serving.infer": RuntimeError,
    # buddy-checkpoint tier: one window-boundary snapshot is about to
    # be put_blob'd to the buddy host (a raise here must leave the
    # PREVIOUS generation on the coord server, still restorable)
    "buddy.send": ConnectionError,
    # buddy restore about to decode an adopted snapshot (a raise here
    # is a torn snapshot: the pod must fall back to the disk rewind
    # with reason="snapshot_torn", never adopt half-decoded state)
    "buddy.restore": RuntimeError,
    # p2p buddy mailbox: the window snapshot (full or delta) is about
    # to be streamed into the ring buddy's mailbox endpoint (a raise
    # here is a torn stream: the buddy never acks, the coordinator
    # metadata row is NOT advanced, and restore must plan buddy_stale
    # -> disk, never elect the half-written payload)
    "buddy.p2p_send": ConnectionError,
    # p2p restore about to pull the snapshot host-to-host from the
    # buddy's mailbox (a raise here must resolve to the typed
    # snapshot_torn disk fallback, never a hang or a partial adopt)
    "buddy.p2p_fetch": ConnectionError,
    # buddy mailbox about to apply ONE delta link while reconstructing
    # a chained snapshot (a raise here is a broken chain: reconstruct
    # fails typed, the adopter falls back to disk, and the next send
    # is forced full)
    "buddy.delta_apply": RuntimeError,
}

# exception classes a ``raise=ExcName`` arg may name
_ERROR_CLASSES = {
    c.__name__: c
    for c in (ConnectionError, ConnectionResetError, OSError,
              TimeoutError, FloatingPointError, RuntimeError,
              ValueError, FaultInjectedError)
}

# ``drop`` sentinel: distinct from None (the unarmed fast path returns
# the payload verbatim, and most sites pass payload=None)
DROP = object()


class FailSpec(object):
    """One parsed failpoint schedule (see module docstring syntax)."""

    _ACTIONS = ("raise", "delay", "drop", "corrupt", "flip")

    def __init__(self, site, action, arg=None, at=None, at_plus=False,
                 prob=None, host=None):
        if site not in SITES:
            raise ValueError(
                "unknown failpoint site %r (catalog: %s)"
                % (site, ", ".join(sorted(SITES))))
        if action not in self._ACTIONS:
            raise ValueError(
                "unknown failpoint action %r (have %s)"
                % (action, ", ".join(self._ACTIONS)))
        if action in ("corrupt", "flip") and not arg:
            raise ValueError(
                "%s needs the target array name: %s:%s=<array>"
                % (action, site, action))
        self.site, self.action, self.arg = site, action, arg
        self.at, self.at_plus, self.prob = at, at_plus, prob
        self.host = None if host is None else str(host)

    @classmethod
    def parse(cls, text):
        text = text.strip()
        if ":" not in text:
            raise ValueError(
                "failpoint spec %r needs the form "
                "site:action[=arg][@N|@N+|~p][^host]" % text)
        site, rest = text.split(":", 1)
        host = None
        if "^" in rest:
            rest, host = rest.rsplit("^", 1)
        at = prob = arg = None
        at_plus = False
        if "@" in rest:
            rest, n = rest.rsplit("@", 1)
            if n.endswith("+"):
                at_plus, n = True, n[:-1]
            at = int(n)
        elif "~" in rest:
            rest, p = rest.rsplit("~", 1)
            prob = float(p)
        if "=" in rest:
            rest, arg = rest.split("=", 1)
        return cls(site.strip(), rest.strip(), arg=arg, at=at,
                   at_plus=at_plus, prob=prob, host=host)

    def matches(self, visit, host, rng):
        if self.host is not None and (host is None
                                      or str(host) != self.host):
            return False
        if self.prob is not None:
            return rng.random() < self.prob
        if self.at is None:
            return True
        return visit >= self.at if self.at_plus else visit == self.at

    def __repr__(self):
        tail = ""
        if self.arg is not None:
            tail += "=%s" % self.arg
        if self.prob is not None:
            tail += "~%g" % self.prob
        elif self.at is not None:
            tail += "@%d%s" % (self.at, "+" if self.at_plus else "")
        if self.host is not None:
            tail += "^%s" % self.host
        return "FailSpec(%s:%s%s)" % (self.site, self.action, tail)


# ---------------------------------------------------------------------------
# registry state
# ---------------------------------------------------------------------------

# THE fast path: hit() tests this one module global and returns.  Arm /
# disarm are the only writers.  Everything else lives behind _lock.
_armed = False

_lock = threading.Lock()
_specs = []            # armed FailSpecs
_visits = {}           # (site, host_str_or_None) -> visit count
_fired = {}            # site -> number of times any action fired
_rng = random.Random(0)


def _host_tag():
    """Fallback host context: the ``host`` tag from
    resilience.context() (PodResilientTrainer sets it per host
    thread)."""
    from . import resilience
    tags = getattr(resilience._tls, "tags", None)
    return None if not tags else tags.get("host")


def armed():
    """True when any failpoint schedule is live (env or programmatic)."""
    return _armed


def schedules():
    """The armed FailSpecs (a copy — test introspection)."""
    with _lock:
        return list(_specs)


def hits_total():
    """{site: number of times a schedule FIRED an action there}."""
    with _lock:
        return dict(_fired)


def reset_counters():
    with _lock:
        _visits.clear()
        _fired.clear()


def arm(specs, seed=None):
    """Arm failpoint schedules (replacing any armed set).

    ``specs``: a spec string (``;``/``,`` separated), an iterable of
    spec strings/FailSpecs, or empty to disarm.  Returns the parsed
    list.  Prefer the :func:`failpoints` context manager in tests."""
    global _armed
    parsed = _parse_specs(specs)
    with _lock:
        _specs[:] = parsed
        if seed is not None:
            _rng.seed(seed)
        _armed = bool(_specs)
    return parsed


def disarm():
    """Remove every schedule; hit() returns to the no-op fast path."""
    global _armed
    with _lock:
        _specs[:] = []
        _armed = False


def _parse_specs(specs):
    if not specs:
        return []
    if isinstance(specs, str):
        parts = [s for chunk in specs.split(";")
                 for s in chunk.split(",") if s.strip()]
        return [FailSpec.parse(s) for s in parts]
    out = []
    for s in specs:
        out.append(s if isinstance(s, FailSpec) else FailSpec.parse(s))
    return out


@contextlib.contextmanager
def failpoints(specs, seed=0):
    """Context manager: arm ``specs`` for the enclosed block, restore
    the previous armed set (and counters) after."""
    global _armed
    parsed = _parse_specs(specs)
    with _lock:
        old_specs = list(_specs)
        old_armed = _armed
        old_visits, old_fired = dict(_visits), dict(_fired)
        _specs[:] = parsed
        _visits.clear()
        _fired.clear()
        _rng.seed(seed)
        _armed = bool(_specs)
    try:
        yield
    finally:
        with _lock:
            _specs[:] = old_specs
            _visits.clear()
            _visits.update(old_visits)
            _fired.clear()
            _fired.update(old_fired)
            _armed = old_armed


# ---------------------------------------------------------------------------
# env arming (shared PADDLE_TPU_FAULTS with the legacy plane)
# ---------------------------------------------------------------------------

def _env_specs():
    """Dotted-site specs from PADDLE_TPU_FAULTS (legacy bare points are
    the resilience.FaultInjector's share of the var)."""
    raw = os.environ.get("PADDLE_TPU_FAULTS", "")
    if not raw:
        return []
    parts = [s for chunk in raw.split(";")
             for s in chunk.split(",") if s.strip()]
    mine = [s for s in parts if "." in s.strip().split(":", 1)[0]]
    return [FailSpec.parse(s) for s in mine]


def reload_env():
    """Re-read PADDLE_TPU_FAULTS (+ PADDLE_TPU_FAULT_SEED) and arm the
    dotted-site specs found there.  Called at import; call again after
    mutating the env in-process."""
    seed = int(os.environ.get("PADDLE_TPU_FAULT_SEED", "0") or 0)
    return arm(_env_specs(), seed=seed)


# ---------------------------------------------------------------------------
# the hit path
# ---------------------------------------------------------------------------

def hit(site, payload=None, host=None):
    """Failpoint site marker.

    Unarmed (production): returns ``payload`` after one bool test.
    Armed: counts the visit for ``(site, host)`` and applies every
    matching schedule — may raise, sleep, return :data:`DROP`, or
    return a corrupted copy of ``payload``."""
    if not _armed:
        return payload
    return _hit_armed(site, payload, host)


def _hit_armed(site, payload, host):
    if site not in SITES:
        raise ValueError("failpoint hit at uncatalogued site %r "
                         "(catalog: %s)" % (site, sorted(SITES)))
    if host is None:
        host = _host_tag()
    hkey = None if host is None else str(host)
    with _lock:
        n = _visits.get((site, hkey), 0) + 1
        _visits[(site, hkey)] = n
        matched = [s for s in _specs
                   if s.site == site and s.matches(n, hkey, _rng)]
        if matched:
            _fired[site] = _fired.get(site, 0) + len(matched)
    if not matched:
        return payload
    from . import resilience
    dropped = False
    for spec in matched:
        resilience.record_event("failpoint", site=site, action=spec.action,
                                visit=n, **({} if hkey is None
                                            else {"host": hkey}))
        if spec.action == "raise":
            exc_name, _, msg = (spec.arg or "").partition("/")
            exc = SITES[site] if not exc_name \
                else _ERROR_CLASSES.get(exc_name)
            if exc is None:
                raise ValueError("failpoint raise=%r names no known "
                                 "error class (have %s)"
                                 % (exc_name, sorted(_ERROR_CLASSES)))
            raise exc(msg or "failpoint %s fired (visit %d%s)"
                      % (site, n, "" if hkey is None
                         else ", host %s" % hkey))
        if spec.action == "delay":
            import time
            time.sleep(float(spec.arg) if spec.arg else 0.05)
        elif spec.action == "drop":
            dropped = True
        elif spec.action in ("corrupt", "flip"):
            payload = _corrupt(payload, spec.arg, flip=spec.action == "flip")
    return DROP if dropped else payload


def _corrupt(payload, name, flip=False):
    """Return a copy of dict ``payload`` with one element of array
    ``name`` NaN-poisoned (or one low bit flipped).  A payload that
    is not a dict, or has no such array, passes through untouched —
    a mis-aimed corrupt schedule must not crash the site."""
    import numpy as np
    if not isinstance(payload, dict) or name not in payload:
        return payload
    arr = np.array(payload[name], copy=True)
    if arr.size == 0:
        return payload
    flat = arr.reshape(-1)
    if flip:
        if arr.dtype.kind in "fc":
            # flip one mantissa bit of element 0: silently wrong but
            # still finite — the SDC shape no finite-mask can see
            as_int = flat[:1].view(
                np.uint32 if arr.dtype.itemsize == 4 else np.uint64)
            as_int[...] = as_int ^ 1
        elif arr.dtype.kind in "iu":
            flat[0] = flat[0] ^ 1
    else:
        if arr.dtype.kind == "f":
            flat[0] = np.nan
        elif arr.dtype.kind == "c":
            flat[0] = complex(np.nan, np.nan)
        else:   # integer arrays can't hold NaN; saturate instead
            flat[0] = np.iinfo(arr.dtype).max
    out = dict(payload)
    out[name] = arr
    return out


# arm from the environment at import: a process launched with
# PADDLE_TPU_FAULTS= set (the chaos soaks' child processes) is armed
# before any site is hit, with zero per-hit env reads afterwards.
reload_env()
