"""CompiledProgram / BuildStrategy — whole-program pjit lowering.

Reference parity: python/paddle/fluid/compiler.py + parallel_executor.py +
framework/details/build_strategy.cc. The reference's ParallelExecutor fuses
the SSA graph and inserts NCCL allreduce ops; here the SAME role is played by
pjit over a jax.sharding.Mesh: parameters/feeds get NamedShardings, XLA
partitions the single fused HLO and inserts ICI collectives (AllReduce/
AllGather/ReduceScatter) automatically — the north-star design.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _env_timeout_default():
    """Fleet-wide watchdog arming without code changes: BuildStrategy's
    collective_timeout_s defaults to PADDLE_TPU_COLLECTIVE_TIMEOUT_S
    (seconds; unset/empty = no guard)."""
    raw = os.environ.get("PADDLE_TPU_COLLECTIVE_TIMEOUT_S", "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            "PADDLE_TPU_COLLECTIVE_TIMEOUT_S=%r is not a number of "
            "seconds (use e.g. '30' or '12.5', or unset for no guard)"
            % raw)


class BuildStrategy(object):
    """Knobs mirroring reference BuildStrategy, TPU-reinterpreted:
      - mesh_axes: dict axis name -> size, e.g. {"dp": 2, "mp": 4}
      - data_axis: mesh axis feeds are batch-sharded over (default "dp")
      - check_numerics: insert NaN/Inf guards (reference check_nan_inf)
    Reference flags like fuse_all_reduce_ops / memory_optimize are
    no-ops: XLA fuses and plans memory itself (kept for API parity)."""

    def __init__(self):
        self.mesh_axes = None
        self.data_axis = "dp"
        self.check_numerics = False
        # halt detection: bound each step's completion (None = no guard);
        # consumed by the run_step watchdog (framework/watchdog.py)
        self.collective_timeout_s = _env_timeout_default()
        # block-quantized data-parallel gradient sync (EQuARX, PAPERS.md):
        # the step is lowered through shard_map over data_axis and every
        # parameter gradient is synced quantize -> psum -> dequantize
        # (int8 payload + per-block fp32 scale) instead of riding pjit's
        # implicit full-width psum. Gradient-merge-aware: accumulation
        # buffers add the already-synced fp32 value, so only the
        # cross-host sync is quantized. Pure-dp meshes only (every other
        # axis must have size 1); fetches are dp-averaged (float) /
        # AND-ed (bool flags). Wire accounting lands in
        # resilience.metrics() as collective_bytes_total{kind=raw|wire}.
        self.quantize_collectives = False
        self.quantize_block_size = 256
        self.quantize_bits = 8
        # gradients below this element count ride the exact full-width
        # sync (sub-block payloads cost MORE quantized); None = one block
        self.quantize_min_size = None
        # Pallas kernel dispatch (ops/pallas): ops named here trace
        # through the fused Pallas kernels — e.g. use_pallas =
        # {"softmax_with_cross_entropy", "adam", "layer_norm"} — with
        # per-shape XLA fallback when a shape cannot tile. Part of the
        # compile-cache token: toggling re-lowers the step.
        self.use_pallas = frozenset()
        # autotune-cache source for the Pallas block configs: a JSON
        # path or an ops.pallas.autotune.AutotuneCache (tools/autotune.py
        # writes it). None = kernel-default block sizes everywhere.
        self.pallas_tune_cache = None
        # parity no-ops
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.enable_inplace = True
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy(object):
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = True


def make_mesh(mesh_axes, devices=None):
    devices = devices if devices is not None else jax.devices()
    sizes = list(mesh_axes.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError("mesh %r needs %d devices, only %d available"
                         % (mesh_axes, n, len(devices)))
    dev_array = np.array(devices[:n]).reshape(sizes)
    return Mesh(dev_array, tuple(mesh_axes.keys()))


def _place_feed(v, sharding):
    """Stage one feed onto the mesh.

    Single-host: a plain sharded device_put.  Multi-host (jax.distributed
    initialized, mesh spanning several processes): each host passes only
    its LOCAL batch rows and the global array is assembled from the
    process-local shards — the TPU-native replacement for the reference's
    per-trainer reader splits (trainer_id/num_trainers slicing in
    distribute_transpiler).  Batch-split feeds use the local-shard path;
    replicated feeds (P()) must carry identical data on every host.
    """
    if jax.process_count() > 1 and sharding.spec and \
            any(a is not None for a in sharding.spec):
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(v))
    return jax.device_put(v, sharding)


class CompiledProgram(object):
    """fluid.CompiledProgram work-alike.

    with_data_parallel(...) without an explicit mesh shards the batch over
    all devices ("dp" axis) — the direct analogue of the reference's
    all-device data parallelism via NCCL allreduce.
    """

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = ExecutionStrategy()
        self._mesh = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        if self._build_strategy.mesh_axes is None:
            self._build_strategy.mesh_axes = {"dp": len(places or
                                                        jax.devices())}
        return self

    def with_mesh(self, mesh_axes, devices=None):
        """TPU-native entry: explicit mesh, e.g. {"dp": 2, "mp": 4}."""
        self._build_strategy.mesh_axes = dict(mesh_axes)
        self._devices = devices
        return self

    def set_mesh_axes(self, mesh_axes, devices=None):
        """Re-target onto a new mesh topology (elastic shrink/grow).

        Drops the cached Mesh so the next run builds one over the new
        axes. The Executor's step cache is keyed by the axes
        (:meth:`_cache_token`), so returning to a previously-seen
        topology — shrink -> grow -> shrink — re-uses that topology's
        compiled executable instead of recompiling."""
        self._build_strategy.mesh_axes = dict(mesh_axes)
        if devices is not None:
            self._devices = devices
        self._mesh = None
        return self

    # ------------------------------------------------------------------
    def _cache_token(self):
        bs = self._build_strategy
        tune = getattr(bs, "pallas_tune_cache", None)
        if tune is not None:
            # identity = path + file stat: re-running tools/autotune.py
            # into the same file must re-lower in a live process (a
            # stale executable would keep the old block configs)
            path = str(getattr(tune, "path", tune))
            try:
                st = os.stat(path)
                tune_tok = (path, st.st_mtime_ns, st.st_size)
            except OSError:
                tune_tok = (path, None, None)
        else:
            tune_tok = None
        return (tuple(sorted((bs.mesh_axes or {}).items())), bs.data_axis,
                getattr(bs, "collective_timeout_s", None),
                (getattr(bs, "quantize_collectives", False),
                 getattr(bs, "quantize_block_size", 256),
                 getattr(bs, "quantize_bits", 8),
                 getattr(bs, "quantize_min_size", None)),
                # Pallas dispatch is baked into the traced step: both the
                # op set and the tuning-cache identity must key the
                # executable
                (tuple(sorted(getattr(bs, "use_pallas", ()) or ())),
                 tune_tok))

    def _mesh_obj(self):
        if self._mesh is None:
            self._mesh = make_mesh(self._build_strategy.mesh_axes,
                                   getattr(self, "_devices", None))
        return self._mesh

    def _var_sharding(self, name, mesh):
        blk = self._program.global_block()
        var = blk._find_var_recursive(name)
        axes = set(mesh.axis_names)
        if var is not None and var.sharding:
            # every annotation site (fleet ZeRO, transpiler tables,
            # tp attrs) meets the REAL mesh here: drop any axis the
            # mesh doesn't have, and any axis whose dim doesn't divide
            # the mesh size — those dims stay replicated instead of
            # failing the jit with a non-divisible NamedSharding
            spec = []
            shape = var.shape or ()
            for i, a in enumerate(var.sharding):
                if a not in axes:
                    spec.append(None)
                elif i < len(shape) and shape[i] not in (None, -1) and \
                        shape[i] % mesh.shape[a] != 0:
                    spec.append(None)
                else:
                    spec.append(a)
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())  # replicated

    def _feed_sharding(self, name, mesh):
        data_axis = self._build_strategy.data_axis
        if data_axis not in mesh.axis_names:
            return NamedSharding(mesh, P())
        # batch-shard feeds over the data axis — but config-like feeds
        # (e.g. a (3,) task_weight schedule vector) whose leading dim can't
        # split over dp stay replicated
        var = self._program.global_block()._find_var_recursive(name)
        if var is not None and var.shape:
            d0 = var.shape[0]
            if d0 not in (None, -1) and d0 % mesh.shape[data_axis] != 0:
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(data_axis))

    def _build_multi_step(self, multi, state_names, feed_names):
        """Sharded scan window (Executor.run_steps on a CompiledProgram):
        `multi` is the executor-built scan over stacked feeds with the
        state as donated carry. Feed shardings get a replicated leading
        steps axis prepended; collectives inside the step ride ICI once
        per scanned step with zero host round-trips."""
        mesh = self._mesh_obj()
        state_sh = tuple(self._var_sharding(n, mesh) for n in state_names)
        feed_sh = tuple(
            NamedSharding(mesh, P(*((None,) + tuple(s.spec))))
            for s in (self._feed_sharding(n, mesh) for n in feed_names))
        return self._wrap_sharded(multi, mesh, state_sh, feed_sh,
                                  (None, state_sh), window=True)

    def _build_step(self, executor, step, program, state_names, feed_names,
                    feed_vals, check_numerics=False):
        mesh = self._mesh_obj()
        state_sh = tuple(self._var_sharding(n, mesh) for n in state_names)
        feed_sh = tuple(self._feed_sharding(n, mesh) for n in feed_names)
        out_sh = (None, state_sh, None) if check_numerics \
            else (None, state_sh)
        return self._wrap_sharded(step, mesh, state_sh, feed_sh, out_sh)

    # -- quantized collectives --------------------------------------------
    def _quantize_ctx(self, mesh):
        """Build the per-compile QuantizedSyncContext, or None when the
        quantized path does not apply (option off / no data axis)."""
        bs = self._build_strategy
        if not getattr(bs, "quantize_collectives", False):
            return None
        if bs.data_axis not in mesh.axis_names:
            return None
        bad = {a: int(s) for a, s in mesh.shape.items()
               if a != bs.data_axis and int(s) > 1}
        if bad:
            raise ValueError(
                "quantize_collectives lowers the step through shard_map "
                "over the %r axis with LOCAL per-shard semantics, so it "
                "supports pure data-parallel meshes only; model axes %r "
                "would lose their XLA-inserted collectives. Drop the "
                "option or the model axes." % (bs.data_axis, bad))
        from ..ops.collective_ops import QuantizedSyncContext
        return QuantizedSyncContext(
            bs.data_axis,
            block_size=int(getattr(bs, "quantize_block_size", 256)),
            bits=int(getattr(bs, "quantize_bits", 8)),
            min_size=getattr(bs, "quantize_min_size", None))

    def _quantized_fn(self, fn, mesh, state_sh, feed_sh, out_sh, qctx):
        """shard_map the step over the data axis with explicit quantized
        gradient sync (the trace hook fires inside the scope) and
        replicated-consistent outputs: float fetches are dp-averaged
        (local-mean loss -> global-mean loss), bool flags (check_numerics)
        are AND-ed across shards, state passes through untouched — it is
        replicated by construction because every shard applies the same
        synced gradients."""
        from ..ops import collective_ops as cops
        try:
            from jax import shard_map as _sm_mod
            shard_map = _sm_mod
        except ImportError:
            from jax.experimental.shard_map import shard_map
        axis = self._build_strategy.data_axis

        def _spec_of(s):
            return P() if s is None else s.spec

        in_specs = (tuple(s.spec for s in state_sh),
                    tuple(s.spec for s in feed_sh))
        out_specs = jax.tree_util.tree_map(
            _spec_of, out_sh,
            is_leaf=lambda s: s is None or isinstance(s, NamedSharding))

        def _sync_leaf(v):
            if jnp.issubdtype(jnp.result_type(v), jnp.bool_):
                return jnp.all(jax.lax.all_gather(v, axis), axis=0)
            if jnp.issubdtype(jnp.result_type(v), jnp.inexact):
                return jax.lax.pmean(v, axis)
            return v

        def quant_step(state_tuple, feed_tuple):
            with cops.grad_sync_scope(qctx):
                out = fn(state_tuple, feed_tuple)
            head = jax.tree_util.tree_map(_sync_leaf, out[0])
            tail = jax.tree_util.tree_map(_sync_leaf, out[2:])
            return (head, out[1]) + tail

        try:
            return shard_map(quant_step, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
        except TypeError:   # newer jax dropped check_rep
            return shard_map(quant_step, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)

    # -- Pallas kernel dispatch -------------------------------------------
    def _pallas_ctx(self, mesh):
        """Build the per-compile PallasConfig, or None when use_pallas
        is empty. The config carries the mesh axes + backend so the
        autotune cache is consulted under the same key the sweep wrote."""
        bs = self._build_strategy
        ops = getattr(bs, "use_pallas", None)
        if not ops:
            return None
        from ..ops import pallas_dispatch as pd
        tune = getattr(bs, "pallas_tune_cache", None)
        if tune is not None and not hasattr(tune, "lookup"):
            from ..ops.pallas.autotune import AutotuneCache
            tune = AutotuneCache(str(tune))
        try:
            backend = next(iter(mesh.devices.flat)).platform
        except Exception:  # pragma: no cover - exotic mesh
            backend = jax.default_backend()
        return pd.PallasConfig(ops, tuning=tune,
                               mesh_axes=dict(bs.mesh_axes or {}),
                               backend=backend)

    def _wrap_sharded(self, fn, mesh, state_sh, feed_sh, out_sh,
                      window=False):
        """Shared step/window machinery: jit over the mesh, stage inputs
        onto their shardings, and arm the one-behind collective-timeout
        watchdog. With quantize_collectives on, the fn is first lowered
        through shard_map with quantized gradient sync; the per-step wire
        accounting (static, accumulated at trace time) is recorded per
        dispatch (x window length for run_steps windows). With use_pallas
        set, the trace runs inside the Pallas dispatch scope so the wired
        op kernels route to their fused implementations."""
        qctx = self._quantize_ctx(mesh)
        if qctx is not None:
            fn = self._quantized_fn(fn, mesh, state_sh, feed_sh, out_sh,
                                    qctx)
        pctx = self._pallas_ctx(mesh)
        if pctx is not None:
            from ..ops import pallas_dispatch as pd
            inner = fn

            def fn(state_tuple, feed_tuple, _inner=inner):
                # the scope only matters while jit TRACES _inner; entering
                # it per call is a few thread-local writes
                with pd.scope(pctx):
                    return _inner(state_tuple, feed_tuple)
        jitted = jax.jit(fn, in_shardings=(state_sh, feed_sh),
                         out_shardings=out_sh, donate_argnums=(0,))
        timeout_s = getattr(self._build_strategy, "collective_timeout_s",
                            None)
        pending = []  # previous call's outputs (one-behind watchdog)

        def run_step(state_vals, feed_tuple):
            with mesh:
                if timeout_s is not None and pending:
                    # Bound-wait on the PREVIOUS dispatch so async
                    # dispatch (host stages batch N+1 while the chip runs
                    # batch N) survives; a hung collective surfaces at
                    # the next call's entry — same one-step-late
                    # semantics as the reference's NCCL watchdog thread.
                    from .watchdog import wait_with_timeout
                    wait_with_timeout(
                        pending.pop(), timeout_s,
                        what="CompiledProgram step over mesh %r"
                        % (tuple(mesh.axis_names),))
                placed_state = tuple(
                    v if isinstance(v, jax.Array) and
                    getattr(v, "sharding", None) == s
                    else jax.device_put(v, s)
                    for v, s in zip(state_vals, state_sh))
                placed_feed = tuple(
                    _place_feed(v, s)
                    for v, s in zip(feed_tuple, feed_sh))
                out = jitted(placed_state, placed_feed)
                if timeout_s is not None:
                    pending.append(out)
                if qctx is not None and qctx.raw_bytes:
                    # static per-step totals (populated by the first
                    # call's trace), multiplied by the window length:
                    # one record per dispatch, zero device syncs
                    from . import resilience
                    n = int(np.shape(feed_tuple[0])[0]) \
                        if window and feed_tuple else 1
                    resilience.record_bytes("collective",
                                            qctx.raw_bytes * n,
                                            qctx.wire_bytes * n)
                return out
        return run_step
