"""CompiledProgram / BuildStrategy — whole-program pjit lowering.

Reference parity: python/paddle/fluid/compiler.py + parallel_executor.py +
framework/details/build_strategy.cc. The reference's ParallelExecutor fuses
the SSA graph and inserts NCCL allreduce ops; here the SAME role is played by
pjit over a jax.sharding.Mesh: parameters/feeds get NamedShardings, XLA
partitions the single fused HLO and inserts ICI collectives (AllReduce/
AllGather/ReduceScatter) automatically — the north-star design.
"""
import os
import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# XLA's CPU backend runs MANUAL collectives (shard_map ppermute /
# all_gather — "cross_module" kind) through a process-global rendezvous:
# two executions in flight from different threads interleave their
# per-device participant arrivals across run_ids and deadlock (observed
# live: pipeline steps from 3 simulated pod hosts each stuck waiting for
# "all participants"). Executions that embed manual collectives
# therefore serialize through this lock ON CPU ONLY — real accelerator
# backends rendezvous per-execution, and production pods are one
# process per host anyway.
_MANUAL_COLLECTIVE_LOCK = threading.Lock()


def _env_verify_default():
    """Suite-wide verifier arming without code changes:
    BuildStrategy.verify_program defaults to PADDLE_TPU_VERIFY
    ("strict" | "warn" | "off"; unset/unknown = "warn" — diagnostics
    are logged, never fatal). The test suite pins "strict"."""
    from .analysis import env_verify_mode
    return env_verify_mode()


def verify_for_compile(program, build_strategy=None, feeds=None,
                       fetch_names=None, source="compile"):
    """Run the Program verifier at a compile seam (framework/analysis).

    Mode comes from BuildStrategy.verify_program (env default for the
    plain-Executor path): "off" returns immediately — byte-for-byte
    inert on the compile path; "warn" logs errors/warnings and records
    the analysis metrics; "strict" raises ProgramVerificationError
    when any error-severity diagnostic survives, listing ALL of them.

    Memoized per (program version, mode, mesh, feed/fetch signature) on
    the program object, so only compile-cache misses pay the walk and
    repeat dispatches cost one dict probe."""
    mode = getattr(build_strategy, "verify_program", None) \
        if build_strategy is not None else None
    if mode is None:
        mode = _env_verify_default()
    if mode == "off":
        return None
    feed_sig = None if feeds is None else tuple(
        sorted((k, tuple(np.shape(v)) if not isinstance(v, tuple)
                else v) for k, v in feeds.items()))
    bs = build_strategy
    if bs is None:
        mesh, strat_sig = None, None
    else:
        mesh = getattr(bs, "mesh_axes", None)
        # every strategy knob a pass consumes joins the memo key — two
        # strategies sharing one Program must never share a verdict
        strat_sig = (getattr(bs, "data_axis", "dp"),
                     getattr(bs, "quantize_collectives", False),
                     getattr(bs, "pp_stages", None),
                     getattr(bs, "pp_micro_batches", 1),
                     getattr(bs, "pp_schedule", "1f1b"),
                     getattr(bs, "pp_recut_slots", None))
    key = (program._version, mode,
           None if mesh is None else tuple(sorted(mesh.items())),
           strat_sig, feed_sig,
           None if fetch_names is None else tuple(fetch_names))
    cache = getattr(program, "_verify_cache", None)
    if cache is None:
        cache = program._verify_cache = {}
    if key in cache:
        result = cache[key]
    else:
        # evict verdicts of older program versions — a mutate-run loop
        # must not accumulate one AnalysisResult per historical version
        for k in [k for k in cache if k[0] != program._version]:
            del cache[k]
        from . import analysis
        result = analysis.verify_program(
            program, feeds=feeds, fetch_list=fetch_names,
            build_strategy=build_strategy)
        analysis.report(result, mode=mode, source=source)
        cache[key] = result
        if result.errors() or result.warnings():
            import logging
            logging.getLogger("paddle_tpu").warning(
                "program verification (%s mode): %s", mode,
                result.summary())
    if mode == "strict" and result.errors():
        from .analysis import ProgramVerificationError
        raise ProgramVerificationError(result)
    return result


def _env_timeout_default():
    """Fleet-wide watchdog arming without code changes: BuildStrategy's
    collective_timeout_s defaults to PADDLE_TPU_COLLECTIVE_TIMEOUT_S
    (seconds; unset/empty = no guard)."""
    raw = os.environ.get("PADDLE_TPU_COLLECTIVE_TIMEOUT_S", "").strip()
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            "PADDLE_TPU_COLLECTIVE_TIMEOUT_S=%r is not a number of "
            "seconds (use e.g. '30' or '12.5', or unset for no guard)"
            % raw)


class BuildStrategy(object):
    """Knobs mirroring reference BuildStrategy, TPU-reinterpreted:
      - mesh_axes: dict axis name -> size, e.g. {"dp": 2, "mp": 4}
      - data_axis: mesh axis feeds are batch-sharded over (default "dp")
      - check_numerics: insert NaN/Inf guards (reference check_nan_inf)
      - pp_stages / pp_micro_batches / pp_schedule: pipeline parallelism
        as a first-class mesh axis (see the pipeline section below)
    Any knob can be passed as a constructor kwarg:
    ``BuildStrategy(pp_stages=2, pp_schedule="1f1b")``.
    Reference flags like fuse_all_reduce_ops / memory_optimize are
    no-ops: XLA fuses and plans memory itself (kept for API parity)."""

    def __init__(self, **kw):
        self.mesh_axes = None
        self.data_axis = "dp"
        self.check_numerics = False
        # what happens when check_numerics trips (framework/executor):
        #   "raise"  -- today's behavior: FloatingPointError, state
        #               already written back (donated buffers), caller
        #               (ResilientTrainer) restores. The in-graph guard
        #               also LOCALIZES the first offending fetch/var by
        #               name, so the error and the numeric_fault event
        #               say WHICH tensor blew up, not just "somewhere".
        #   "skip"   -- discard the step in-graph: every state leaf
        #               (optimizer moments + PRNG counter included)
        #               reverts to its pre-step value under a jnp.where
        #               on the all-finite flag, the data cursor moves
        #               past the poison batch, and a numeric_fault
        #               event names the culprit. Bounded by
        #               numeric_skip_budget CONSECUTIVE skips — a
        #               persistent fault escalates to
        #               SkipBudgetExceededError instead of silently
        #               dropping the stream.
        #   "rewind" -- raise resilience.NumericFaultError (a
        #               FloatingPointError carrying step + culprit):
        #               the (Pod/Elastic) trainer's existing
        #               consensus-rewind recovery restores the last
        #               checkpoint and REPLAYS WITH THE POISON BATCH
        #               SKIPPED, so the recovered trajectory equals the
        #               uninterrupted run without that batch, bitwise.
        # Implies check_numerics when set to "skip"/"rewind". Part of
        # the compile-cache token: the lowered step differs per policy.
        self.numeric_policy = "raise"
        # max CONSECUTIVE steps numeric_policy="skip" may discard
        # before escalating (a clean step resets the streak)
        self.numeric_skip_budget = 3
        # halt detection: bound each step's completion (None = no guard);
        # consumed by the run_step watchdog (framework/watchdog.py)
        self.collective_timeout_s = _env_timeout_default()
        # block-quantized data-parallel gradient sync (EQuARX, PAPERS.md):
        # the step is lowered through shard_map over data_axis and every
        # parameter gradient is synced quantize -> psum -> dequantize
        # (int8 payload + per-block fp32 scale) instead of riding pjit's
        # implicit full-width psum. Gradient-merge-aware: accumulation
        # buffers add the already-synced fp32 value, so only the
        # cross-host sync is quantized. Pure-dp meshes only (every other
        # axis must have size 1); fetches are dp-averaged (float) /
        # AND-ed (bool flags). Wire accounting lands in
        # resilience.metrics() as collective_bytes_total{kind=raw|wire}.
        self.quantize_collectives = False
        self.quantize_block_size = 256
        self.quantize_bits = 8
        # gradients below this element count ride the exact full-width
        # sync (sub-block payloads cost MORE quantized); None = one block
        self.quantize_min_size = None
        # Pallas kernel dispatch (ops/pallas): ops named here trace
        # through the fused Pallas kernels — e.g. use_pallas =
        # {"softmax_with_cross_entropy", "adam", "layer_norm"} — with
        # per-shape XLA fallback when a shape cannot tile. Part of the
        # compile-cache token: toggling re-lowers the step.
        self.use_pallas = frozenset()
        # autotune-cache source for the Pallas block configs: a JSON
        # path or an ops.pallas.autotune.AutotuneCache (tools/autotune.py
        # writes it). None = under kernel_policy "auto", the committed
        # per-backend cache tools/tuned/{backend}.json when it exists;
        # otherwise kernel-default block sizes everywhere.
        self.pallas_tune_cache = None
        # ONE front door for kernel selection (ISSUE 13), replacing the
        # three independent knobs (use_pallas / pallas_tune_cache /
        # per-op quant attrs — all still honored as overrides):
        #   "auto"   -- resolve XLA vs Pallas(config) vs quantized
        #               variant PER CALL SITE at trace time: banked
        #               measured verdicts first (mesh-exact, then the
        #               topology-agnostic key), cost-model-predicted
        #               configs on a cache miss. Engages for the ops in
        #               use_pallas (resolving the banked in-repo cache
        #               when none is given); with use_pallas empty it
        #               engages ALL Pallas-backed ops only when an
        #               EXPLICIT pallas_tune_cache says the operator
        #               has verdicts to apply — no signal, no change.
        #   "xla"    -- force every op onto its XLA lowering (kills
        #               use_pallas for this compile).
        #   "pallas" -- route all Pallas-backed ops (or the use_pallas
        #               subset) through their kernels, cache-informed.
        # Part of the compile-cache token: flipping policy re-lowers.
        self.kernel_policy = "auto"
        # Pipeline parallelism (reference PipelineOptimizer/section_worker,
        # TPU-native): pp_stages=K cuts the traced Program at its
        # pp_stage stamps (or an even op-count auto-cut when unstamped)
        # and lowers the whole fwd+bwd+optimizer step through the
        # GPipe/1F1B ppermute-ring schedules over the mesh's "pp" axis,
        # composing with dp gradient sync (quantize_collectives
        # included) on the data axis. Stage params/optimizer state are
        # stacked (n_stage, ...) and live only on their pp slice of the
        # mesh. pp_micro_batches=M splits each batch into M microbatches
        # (bubble fraction ~ (K-1)/(M+K-1)); pp_schedule picks "1f1b"
        # (bounded activation stash, rematerialized backward) or "gpipe"
        # (autodiff through the forward ring). All three join the
        # compile-cache token: toggling re-lowers.
        self.pp_stages = None
        self.pp_micro_batches = 1
        self.pp_schedule = "1f1b"
        # Elastic pp re-cut (ISSUE 18): n_slots < pp_stages re-stacks the
        # K logical stages over n_slots mesh slots (multiple stages per
        # slot, (n_slots, k_per, ...) stacked state INSIDE the jit; the
        # scope keeps the flat per-stage layout, so checkpoints/elastic
        # state-shipping stay wire-compatible). The mesh's "pp" axis must
        # equal pp_recut_slots while armed. ElasticTrainer arms this on a
        # survivable pp host loss and clears it on re-grow; joins the
        # compile-cache token — a re-cut re-lowers, repeats hit.
        self.pp_recut_slots = None
        # Program IR verification at CompilePlan build time
        # (framework/analysis.py): "strict" fails the compile on any
        # error-severity diagnostic (ALL violations listed, not
        # first-error-wins), "warn" (default; env PADDLE_TPU_VERIFY
        # overrides) logs + exports analysis metrics, "off" skips the
        # verifier entirely. Diagnostics-only — the knob can never
        # change the lowered executable, so it is deliberately NOT part
        # of the compile-cache token (tools/codelint.py allowlists it).
        self.verify_program = _env_verify_default()
        # once-per-k quantized sync for gradient-merge windows (OPT-IN):
        # when a grad-merge accumulator structure is detected, the
        # quantized dp sync moves from every micro step's raw gradient
        # to the MERGE BOUNDARY (the gated merged gradient, under
        # lax.cond on the program's own apply predicate) — k-1 of every
        # k steps ship zero gradient bytes. Accumulation buffers then
        # hold LOCAL fp32 sums (still exact/bitwise per shard), which
        # means they are NOT dp-replicated mid-window: a checkpoint
        # taken off a merge boundary (straggler_ckpt, admission saves)
        # captures one shard's buffer, and a consensus rewind restoring
        # it everywhere drops the other shards' accumulation. Enable
        # only when every snapshot lands on a k-aligned boundary
        # (checkpoint_every % k == 0 and no unscheduled saves) or the
        # run tolerates a non-bitwise merge window across a rewind.
        # False (default) = legacy every-step sync.
        self.quantize_merge_sync = False
        # parity no-ops
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.enable_inplace = True
        self.num_trainers = 1
        self.trainer_id = 0
        for k, v in kw.items():
            if not hasattr(self, k):
                raise TypeError("BuildStrategy has no knob %r" % k)
            setattr(self, k, v)
        if self.numeric_policy not in ("raise", "skip", "rewind"):
            raise ValueError(
                "numeric_policy must be 'raise', 'skip' or 'rewind', "
                "got %r" % (self.numeric_policy,))
        if int(self.numeric_skip_budget) < 1:
            raise ValueError("numeric_skip_budget must be >= 1")
        if self.pp_recut_slots is not None:
            if int(self.pp_recut_slots) < 1:
                raise ValueError("pp_recut_slots must be >= 1 (a re-cut "
                                 "keeps every logical stage resident)")
            if not self.pp_stages:
                raise ValueError(
                    "pp_recut_slots needs pp_stages: the re-cut maps K "
                    "logical stages (pp_stages) onto n_slots mesh slots")


class ExecutionStrategy(object):
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = True


class CompilePlan(object):
    """How a (program, strategy) pair lowers: trace -> cut -> schedule ->
    jit. Retires the old single-jit assumption: the executor consults
    the plan's ``kind`` to route the step build, and ``token`` (mesh
    axes + quantize/pallas knobs + pp cut + schedule) keys its compile
    cache, so toggling the cut or schedule re-lowers while repeat runs
    hit the cached executable.

      kind      -- "single_jit" | "pipeline"
      token     -- the strategy cache token (includes pp knobs)
      cut       -- distributed.pipeline_program.CompiledPPCut (pipeline)
      schedule  -- "1f1b" | "gpipe" (pipeline)
      n_micro   -- microbatches per step (pipeline)
      recut     -- distributed.pipeline_program.RecutPlan when the
                   elastic re-cut is armed (K stages over n_slots < K
                   mesh slots), else None
    """

    __slots__ = ("kind", "token", "cut", "schedule", "n_micro", "recut")

    def __init__(self, kind, token, cut=None, schedule=None, n_micro=1,
                 recut=None):
        self.kind = kind
        self.cut = cut
        self.schedule = schedule
        self.n_micro = int(n_micro)
        self.recut = recut
        # the cut signature joins the token: two programs whose strategy
        # knobs agree but whose cuts differ must not share an executable
        self.token = token if cut is None else token + (cut.signature(),)
        if recut is not None:
            self.token = self.token + (recut.signature(),)


def make_mesh(mesh_axes, devices=None):
    devices = devices if devices is not None else jax.devices()
    sizes = list(mesh_axes.values())
    n = int(np.prod(sizes))
    if n > len(devices):
        raise ValueError("mesh %r needs %d devices, only %d available"
                         % (mesh_axes, n, len(devices)))
    dev_array = np.array(devices[:n]).reshape(sizes)
    return Mesh(dev_array, tuple(mesh_axes.keys()))


def _place_feed(v, sharding):
    """Stage one feed onto the mesh.

    Single-host: a plain sharded device_put.  Multi-host (jax.distributed
    initialized, mesh spanning several processes): each host passes only
    its LOCAL batch rows and the global array is assembled from the
    process-local shards — the TPU-native replacement for the reference's
    per-trainer reader splits (trainer_id/num_trainers slicing in
    distribute_transpiler).  Batch-split feeds use the local-shard path;
    replicated feeds (P()) must carry identical data on every host.
    """
    if jax.process_count() > 1 and sharding.spec and \
            any(a is not None for a in sharding.spec):
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(v))
    return jax.device_put(v, sharding)


class CompiledProgram(object):
    """fluid.CompiledProgram work-alike.

    with_data_parallel(...) without an explicit mesh shards the batch over
    all devices ("dp" axis) — the direct analogue of the reference's
    all-device data parallelism via NCCL allreduce.
    """

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = ExecutionStrategy()
        self._mesh = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        if build_strategy is not None:
            self._build_strategy = build_strategy
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        if self._build_strategy.mesh_axes is None:
            n_dev = len(places or jax.devices())
            k = int(getattr(self._build_strategy, "pp_stages", 0) or 0)
            if k > 1:
                # pp as a first-class axis: "all-device data parallel"
                # on a pipeline strategy means pp x dp over the devices
                self._build_strategy.mesh_axes = {
                    "pp": k, "dp": max(1, n_dev // k)}
            else:
                self._build_strategy.mesh_axes = {"dp": n_dev}
        return self

    def with_mesh(self, mesh_axes, devices=None):
        """TPU-native entry: explicit mesh, e.g. {"dp": 2, "mp": 4}."""
        self._build_strategy.mesh_axes = dict(mesh_axes)
        self._devices = devices
        return self

    def set_mesh_axes(self, mesh_axes, devices=None):
        """Re-target onto a new mesh topology (elastic shrink/grow).

        Drops the cached Mesh so the next run builds one over the new
        axes. The Executor's step cache is keyed by the axes
        (:meth:`_cache_token`), so returning to a previously-seen
        topology — shrink -> grow -> shrink — re-uses that topology's
        compiled executable instead of recompiling."""
        self._build_strategy.mesh_axes = dict(mesh_axes)
        if devices is not None:
            self._devices = devices
        self._mesh = None
        return self

    # ------------------------------------------------------------------
    def _kernel_policy(self):
        bs = self._build_strategy
        from ..ops import pallas_dispatch as pd
        policy = getattr(bs, "kernel_policy", "auto") or "auto"
        if policy not in pd.KERNEL_POLICIES:
            raise ValueError(
                "kernel_policy must be one of %r, got %r"
                % (list(pd.KERNEL_POLICIES), policy))
        return policy

    def _resolve_tune(self):
        """The EFFECTIVE tuned-cache source of this compile: the
        strategy's explicit pallas_tune_cache, or — under kernel_policy
        "auto" with Pallas ops engaged — the committed per-backend
        cache tools/tuned/{backend}.json when it exists (how CI, bench
        rounds and serving replicas share one set of verdicts without
        per-job plumbing). Returns a path/cache-object or None; used by
        BOTH the dispatch-scope build and the compile-cache token, so
        the executable can never outlive the cache it baked in."""
        bs = self._build_strategy
        tune = getattr(bs, "pallas_tune_cache", None)
        if tune is None and self._kernel_policy() == "auto" and \
                getattr(bs, "use_pallas", None):
            from ..ops.pallas.autotune import banked_cache_path
            path = banked_cache_path(jax.default_backend())
            if os.path.exists(path):
                tune = path
        return tune

    def _cache_token(self):
        bs = self._build_strategy
        tune = self._resolve_tune()
        if tune is not None:
            # identity = path + file stat: re-running tools/autotune.py
            # into the same file must re-lower in a live process (a
            # stale executable would keep the old block configs)
            path = str(getattr(tune, "path", tune))
            try:
                st = os.stat(path)
                tune_tok = (path, st.st_mtime_ns, st.st_size)
            except OSError:
                tune_tok = (path, None, None)
        else:
            tune_tok = None
        # the selection layer joins the token too: flipping
        # kernel_policy between compiles, or changing the cost model /
        # candidate space across an upgrade, must re-lower — a stale
        # jitted program would keep the other policy's kernels
        from ..ops.pallas.autotune import selection_fingerprint
        sel_tok = (self._kernel_policy(), selection_fingerprint())
        return (tuple(sorted((bs.mesh_axes or {}).items())), bs.data_axis,
                getattr(bs, "collective_timeout_s", None),
                (getattr(bs, "quantize_collectives", False),
                 getattr(bs, "quantize_block_size", 256),
                 getattr(bs, "quantize_bits", 8),
                 getattr(bs, "quantize_min_size", None),
                 getattr(bs, "quantize_merge_sync", False)),
                # Pallas dispatch is baked into the traced step: the op
                # set, the tuning-cache identity AND the selection
                # layer (policy + cost-model fingerprint) must key the
                # executable
                (tuple(sorted(getattr(bs, "use_pallas", ()) or ())),
                 tune_tok, sel_tok),
                # the pipeline cut/schedule selects a whole different
                # lowering — toggling pp_stages or the schedule must
                # re-lower, never reuse a single-jit executable
                (getattr(bs, "pp_stages", None),
                 int(getattr(bs, "pp_micro_batches", 1) or 1),
                 getattr(bs, "pp_schedule", "1f1b"),
                 # the elastic re-cut slot map selects a different
                 # stacking geometry + ring size: arming/clearing it
                 # must re-lower, repeats at the same slot count hit
                 getattr(bs, "pp_recut_slots", None)),
                # numeric_policy changes the lowered step (per-var
                # finite mask, in-graph skip select) — "skip" and
                # "raise" must never share an executable
                getattr(bs, "numeric_policy", "raise"))

    # -- pipeline parallelism ---------------------------------------------
    def _pp_enabled(self):
        bs = self._build_strategy
        if getattr(bs, "pp_stages", None):
            return True
        return int((bs.mesh_axes or {}).get("pp", 1) or 1) > 1

    def compile_plan(self):
        """The lowering route of this (program, strategy) pair — the
        compile plan object: trace -> cut -> schedule -> jit. A plain
        strategy lowers as one jit (kind "single_jit"); a pipeline
        strategy (pp_stages set, or a >1 "pp" mesh axis) cuts the
        program first (kind "pipeline") and the executor routes the
        step through the GPipe/1F1B lowering. The plan's token keys the
        executor step cache: (mesh axes, pp cut, schedule) ride along-
        side the existing strategy token.

        The Program verifier runs HERE, before any lowering work — on
        the pp route that means pipeline misconfiguration surfaces as a
        complete diagnostics list BEFORE extract_compiled_pp_plan's
        first-named-error (framework/analysis.py). Skipped when this
        program version was already verified (the executor's pp seam
        runs a STRONGER feed-ful walk just before calling here — a
        second feed-less walk would only double-count the analysis
        metrics)."""
        cache = getattr(self._program, "_verify_cache", None)
        if not cache or all(k[0] != self._program._version
                            for k in cache):
            verify_for_compile(self._program, self._build_strategy,
                               source="compile_plan")
        if not self._pp_enabled():
            return CompilePlan("single_jit", self._cache_token())
        from ..distributed import pipeline_program as ppp
        bs = self._build_strategy
        if getattr(bs, "numeric_policy", "raise") != "raise":
            raise ValueError(
                "numeric_policy=%r is not supported with pipeline "
                "parallelism yet — the pp lowering keeps raise-only "
                "check_numerics" % (bs.numeric_policy,))
        axes = dict(bs.mesh_axes or {})
        k = int(bs.pp_stages) if getattr(bs, "pp_stages", None) else None
        recut_n = getattr(bs, "pp_recut_slots", None)
        recut_n = int(recut_n) if recut_n else None
        # with the elastic re-cut armed the mesh's pp axis counts SLOTS
        # (one per surviving pp rank), not logical stages
        ring = recut_n if recut_n is not None else k
        if "pp" not in axes:
            if ring is None:
                raise ValueError("pipeline strategy needs pp_stages or a "
                                 "'pp' mesh axis")
            # first-class default: pp x dp over all devices
            n_dev = len(getattr(self, "_devices", None) or jax.devices())
            if axes:
                raise ValueError(
                    "mesh_axes %r has no 'pp' axis but pp_stages=%d is "
                    "set — include pp in the mesh (e.g. {'pp': %d, "
                    "'dp': %d})" % (axes, k, ring, max(1, n_dev // ring)))
            axes = {"pp": ring, "dp": max(1, n_dev // ring)}
            bs.mesh_axes = dict(axes)
        if ring is not None and int(axes["pp"]) != ring:
            if recut_n is not None:
                raise ValueError(
                    "pp_recut_slots=%d does not match the mesh's pp axis "
                    "(%d) — the re-cut mesh carries one slot per "
                    "surviving pp rank" % (recut_n, int(axes["pp"])))
            raise ValueError(
                "pp_stages=%d does not match the mesh's pp axis (%d)"
                % (k, int(axes["pp"])))
        if k is None:
            k = int(axes["pp"])
        schedule = getattr(bs, "pp_schedule", "1f1b")
        n_micro = int(getattr(bs, "pp_micro_batches", 1) or 1)
        cache = getattr(self._program, "_pp_cut_cache", None)
        ck = (k, schedule, n_micro)
        if cache is not None and cache[0] == (self._program._version,) + ck:
            cut = cache[1]
        else:
            cut = ppp.extract_compiled_pp_plan(
                self._program, n_stage=k, schedule=schedule,
                n_micro=n_micro)
            # store POST-extract version: the auto-cut stamps attrs and
            # bumps it once
            self._program._pp_cut_cache = (
                (self._program._version,) + ck, cut)
        # identity re-cut (n_slots == K) lowers through the ordinary
        # 1-stage-per-slot path; n_slots > K raises the typed
        # PPRecutInfeasibleError from recut_plan
        rplan = ppp.recut_plan(k, recut_n) \
            if recut_n is not None and recut_n != k else None
        return CompilePlan("pipeline", self._cache_token(),
                           cut=cut, schedule=schedule, n_micro=n_micro,
                           recut=rplan)

    def _mesh_obj(self):
        if self._mesh is None:
            self._mesh = make_mesh(self._build_strategy.mesh_axes,
                                   getattr(self, "_devices", None))
        return self._mesh

    def _var_sharding(self, name, mesh):
        blk = self._program.global_block()
        var = blk._find_var_recursive(name)
        axes = set(mesh.axis_names)
        if var is not None and var.sharding:
            # every annotation site (fleet ZeRO, transpiler tables,
            # tp attrs) meets the REAL mesh here: drop any axis the
            # mesh doesn't have, and any axis whose dim doesn't divide
            # the mesh size — those dims stay replicated instead of
            # failing the jit with a non-divisible NamedSharding
            spec = []
            shape = var.shape or ()
            for i, a in enumerate(var.sharding):
                if a not in axes:
                    spec.append(None)
                elif i < len(shape) and shape[i] not in (None, -1) and \
                        shape[i] % mesh.shape[a] != 0:
                    spec.append(None)
                else:
                    spec.append(a)
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())  # replicated

    def _feed_sharding(self, name, mesh):
        data_axis = self._build_strategy.data_axis
        if data_axis not in mesh.axis_names:
            return NamedSharding(mesh, P())
        # batch-shard feeds over the data axis — but config-like feeds
        # (e.g. a (3,) task_weight schedule vector) whose leading dim can't
        # split over dp stay replicated
        var = self._program.global_block()._find_var_recursive(name)
        if var is not None and var.shape:
            d0 = var.shape[0]
            if d0 not in (None, -1) and d0 % mesh.shape[data_axis] != 0:
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, P(data_axis))

    def _build_multi_step(self, multi, state_names, feed_names):
        """Sharded scan window (Executor.run_steps on a CompiledProgram):
        `multi` is the executor-built scan over stacked feeds with the
        state as donated carry. Feed shardings get a replicated leading
        steps axis prepended; collectives inside the step ride ICI once
        per scanned step with zero host round-trips."""
        mesh = self._mesh_obj()
        state_sh = tuple(self._var_sharding(n, mesh) for n in state_names)
        feed_sh = tuple(
            NamedSharding(mesh, P(*((None,) + tuple(s.spec))))
            for s in (self._feed_sharding(n, mesh) for n in feed_names))
        return self._wrap_sharded(multi, mesh, state_sh, feed_sh,
                                  (None, state_sh), window=True)

    def _build_step(self, executor, step, program, state_names, feed_names,
                    feed_vals, check_numerics=False):
        mesh = self._mesh_obj()
        state_sh = tuple(self._var_sharding(n, mesh) for n in state_names)
        feed_sh = tuple(self._feed_sharding(n, mesh) for n in feed_names)
        out_sh = (None, state_sh, None) if check_numerics \
            else (None, state_sh)
        return self._wrap_sharded(step, mesh, state_sh, feed_sh, out_sh)

    # -- quantized collectives --------------------------------------------
    def _quantize_ctx(self, mesh, allow_pp=False):
        """Build the per-compile QuantizedSyncContext, or None when the
        quantized path does not apply (option off / no data axis).
        allow_pp: the pipeline lowering runs its own shard_map over
        pp x dp and applies the quantized sync explicitly on the dp
        axis, so a pp axis is fine THERE — everywhere else a >1 model
        axis would silently lose its XLA-inserted collectives."""
        bs = self._build_strategy
        if not getattr(bs, "quantize_collectives", False):
            return None
        if bs.data_axis not in mesh.axis_names:
            return None
        skip = {bs.data_axis} | ({"pp"} if allow_pp else set())
        bad = {a: int(s) for a, s in mesh.shape.items()
               if a not in skip and int(s) > 1}
        if bad:
            raise ValueError(
                "quantize_collectives lowers the step through shard_map "
                "over the %r axis with LOCAL per-shard semantics, so it "
                "supports pure data-parallel meshes only; model axes %r "
                "would lose their XLA-inserted collectives. Drop the "
                "option or the model axes." % (bs.data_axis, bad))
        if getattr(bs, "numeric_policy", "raise") == "skip":
            raise ValueError(
                "numeric_policy='skip' reverts state in-graph from the "
                "GLOBAL all-finite verdict, but the quantized shard_map "
                "lowering evaluates per-shard flags before the sync — "
                "shards could revert divergently. Use "
                "numeric_policy='rewind' (host-side, sees the AND-ed "
                "flag) or disable quantize_collectives.")
        from ..ops.collective_ops import QuantizedSyncContext
        return QuantizedSyncContext(
            bs.data_axis,
            block_size=int(getattr(bs, "quantize_block_size", 256)),
            bits=int(getattr(bs, "quantize_bits", 8)),
            min_size=getattr(bs, "quantize_min_size", None),
            merge_window=bool(getattr(bs, "quantize_merge_sync", False)))

    def _quantized_fn(self, fn, mesh, state_sh, feed_sh, out_sh, qctx):
        """shard_map the step over the data axis with explicit quantized
        gradient sync (the trace hook fires inside the scope) and
        replicated-consistent outputs: float fetches are dp-averaged
        (local-mean loss -> global-mean loss), bool flags (check_numerics)
        are AND-ed across shards, state passes through untouched — it is
        replicated by construction because every shard applies the same
        synced gradients."""
        from ..ops import collective_ops as cops
        try:
            from jax import shard_map as _sm_mod
            shard_map = _sm_mod
        except ImportError:
            from jax.experimental.shard_map import shard_map
        axis = self._build_strategy.data_axis

        def _spec_of(s):
            return P() if s is None else s.spec

        in_specs = (tuple(s.spec for s in state_sh),
                    tuple(s.spec for s in feed_sh))
        out_specs = jax.tree_util.tree_map(
            _spec_of, out_sh,
            is_leaf=lambda s: s is None or isinstance(s, NamedSharding))

        def _sync_leaf(v):
            if jnp.issubdtype(jnp.result_type(v), jnp.bool_):
                return jnp.all(jax.lax.all_gather(v, axis), axis=0)
            if jnp.issubdtype(jnp.result_type(v), jnp.inexact):
                return jax.lax.pmean(v, axis)
            return v

        def quant_step(state_tuple, feed_tuple):
            with cops.grad_sync_scope(qctx):
                out = fn(state_tuple, feed_tuple)
            head = jax.tree_util.tree_map(_sync_leaf, out[0])
            tail = jax.tree_util.tree_map(_sync_leaf, out[2:])
            return (head, out[1]) + tail

        try:
            return shard_map(quant_step, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)
        except TypeError:   # newer jax dropped check_rep
            return shard_map(quant_step, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)

    # -- Pallas kernel dispatch -------------------------------------------
    def _pallas_ctx(self, mesh):
        """Build the per-compile PallasConfig (the KernelChoice layer's
        trace-time state), or None when this compile routes nothing
        through Pallas. The config carries the mesh axes + backend so
        the autotune cache is consulted under the same key the sweep
        wrote; under kernel_policy "auto" it additionally carries the
        cost model fitted from that cache's banked rows, so a
        never-swept shape resolves to a PREDICTED config instead of the
        hardcoded kernel default."""
        bs = self._build_strategy
        from ..ops import pallas_dispatch as pd
        policy = self._kernel_policy()
        ops = frozenset(getattr(bs, "use_pallas", ()) or ())
        if policy == "xla":
            return None
        if not ops:
            if policy == "pallas" or (
                    policy == "auto" and
                    getattr(bs, "pallas_tune_cache", None) is not None):
                ops = frozenset(pd.PALLAS_OPS)
            else:
                return None
        tune = self._resolve_tune()
        if tune is not None and not hasattr(tune, "lookup"):
            from ..ops.pallas.autotune import AutotuneCache
            tune = AutotuneCache(str(tune))
        try:
            backend = next(iter(mesh.devices.flat)).platform
        except Exception:  # pragma: no cover - exotic mesh
            backend = jax.default_backend()
        model = None
        if policy == "auto":
            model = self._cost_model(tune, backend)
        return pd.PallasConfig(ops, tuning=tune,
                               mesh_axes=dict(bs.mesh_axes or {}),
                               backend=backend, cost_model=model,
                               policy=policy)

    def _cost_model(self, tune, backend):
        """The fitted cost model for this compile, memoized per
        (cache identity, backend): refitting reads and regresses the
        whole banked file, so repeat compiles against an unchanged
        cache reuse the fit."""
        from ..ops.pallas.autotune import fit_cost_model
        path = None if tune is None else str(getattr(tune, "path", tune))
        try:
            st = os.stat(path) if path else None
            ident = (path, None if st is None else
                     (st.st_mtime_ns, st.st_size), backend)
        except OSError:
            ident = (path, None, backend)
        memo = getattr(self, "_cm_memo", None)
        if memo is not None and memo[0] == ident:
            return memo[1]
        model = fit_cost_model(tune,
                               interpret=backend not in ("tpu", "axon"))
        self._cm_memo = (ident, model)
        return model

    # -- pipeline lowering -------------------------------------------------
    def _build_pp_step(self, program, cplan, fetch_names, micro_shapes,
                       check_numerics=False, windowed=False):
        """Lower the whole fwd+bwd+optimizer step through the pipeline
        schedule inside ONE shard_map over the pp(xdp) mesh.

        Per pp shard: run this stage's slice of the stacked params
        through the GPipe/1F1B ring (distributed.pipeline local bodies
        — the schedule's own autodiff replaces the program's backward
        section), dp-sync the stage grads (plain pmean, or the
        quantized collectives when quantize_collectives is on), then
        trace the program's OWN update section (optimizer ops, LR
        schedule, gradient-merge accumulation) on the stage-0 template
        over this shard's state slice. Stage state is stacked
        (n_stage, ...) and NamedSharded P("pp") — each stage's params
        and optimizer moments live only on their pp slice of the mesh.

        Returns (state_info, run_step): state_info tells the executor
        how to stack scope state ((stacked_names, stage_cols,
        shared_names, feed_order)); run_step has the usual
        (state_tuple, feed_tuple) dispatch signature."""
        from ..distributed import pipeline_program as ppp
        from ..distributed.pipeline import (pipeline_1f1b_local,
                                            pipeline_gpipe_local,
                                            pipeline_forward_local)
        try:
            from jax import shard_map
        except ImportError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map
        mesh = self._mesh_obj()
        cut = cplan.cut
        plan = cut.plan
        n_stage = plan.n_stage
        rec = cplan.recut
        # with the elastic re-cut armed the ring runs over n_slots SLOTS
        # (each a super-stage iterating its resident logical stages);
        # otherwise one slot per stage, ring size n_stage
        n_ring = rec.n_slots if rec is not None else n_stage
        if int(mesh.shape.get("pp", 0)) != n_ring:
            if rec is not None:
                raise ValueError(
                    "re-cut plan stacks %d pipeline stages over %d slots "
                    "but the mesh 'pp' axis has %d devices — they must "
                    "match" % (n_stage, n_ring,
                               int(mesh.shape.get("pp", 0))))
            raise ValueError(
                "program cuts into %d pipeline stages but the mesh 'pp' "
                "axis has %d devices — they must match"
                % (n_stage, int(mesh.shape.get("pp", 0))))
        bs = self._build_strategy
        dp_axis = bs.data_axis if (bs.data_axis in mesh.axis_names and
                                   mesh.shape[bs.data_axis] > 1) else None
        bad = {a: int(s) for a, s in mesh.shape.items()
               if a not in ("pp", dp_axis) and int(s) > 1}
        if bad:
            raise ValueError(
                "the pipeline lowering supports pp x %s meshes only; "
                "axes %r are unsupported (v1)" % (bs.data_axis, bad))
        qctx = self._quantize_ctx(mesh, allow_pp=True)

        tail_produced = {n for op in plan.tail_ops
                         for n in op.output_names()}
        aux_names = [n for n in fetch_names if n != cut.loss_name]
        unknown = [n for n in aux_names if n not in tail_produced]
        if unknown:
            raise ValueError(
                "pipeline fetch_list entries must be the loss or vars "
                "computed by the unstamped loss section; %r are not "
                "(stage activations stay sharded on the pp ring)"
                % (unknown,))

        stage_fn = ppp.make_stage_fn(program, plan)
        if rec is not None:
            # the ring body sees ONE callable per slot; the wrapper
            # iterates the slot's resident stages over its (k_per, ...)
            # rows of the stacked state
            stage_fn = ppp.make_slot_stage_fn(stage_fn, rec, "pp")
        loss_fn = ppp.make_loss_fn(program, plan)
        tail_fn = ppp.make_tail_fn(program, plan, tuple(aux_names)) \
            if aux_names else None
        update = ppp.make_update_trace_fn(program, cut)
        stacked_names = sorted(cut.stage_state)
        shared_names = list(cut.shared_state)
        n_stacked = len(stacked_names)
        tmpl_params = list(plan.template_params)
        n_micro = plan.n_micro
        feed_order = [plan.x_feed] + list(plan.y_feeds)
        from .trace import GRAD_SUFFIX

        if cplan.schedule == "1f1b":
            sched = pipeline_1f1b_local(stage_fn, loss_fn, n_ring,
                                        n_micro, "pp", dp_axis)
        elif cplan.schedule == "gpipe":
            sched = pipeline_gpipe_local(stage_fn, loss_fn, n_ring,
                                         n_micro, "pp", dp_axis)
        else:
            raise ValueError("unknown pp_schedule %r" % cplan.schedule)
        fwd = pipeline_forward_local(stage_fn, n_ring, n_micro, "pp",
                                     dp_axis) if tail_fn else None

        def _unmicro(a):
            return a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:])

        def _feed_spec(name):
            # (n_micro, micro_batch, ...): micro dim replicated, batch
            # dim dp-sharded when it divides; an indivisible batch stays
            # replicated (every dp shard computes the same full batch)
            shape = micro_shapes[name]
            mb = shape[2 if windowed else 1] if len(shape) > \
                (2 if windowed else 1) else None
            if dp_axis is not None and mb is not None \
                    and mb % mesh.shape[dp_axis] == 0:
                return P(None, dp_axis)
            return P()
        feed_specs = tuple(_feed_spec(n) for n in feed_order)
        feed_sharded = tuple(dp_axis in tuple(s) for s in feed_specs)

        def _gather_rows(a, sharded):
            # reassemble the FULL batch on every dp shard (contiguous
            # dim-1 blocks, so tiled all_gather restores serial order)
            if dp_axis is None or not sharded:
                return a
            return jax.lax.all_gather(a, dp_axis, axis=1, tiled=True)

        def local_step(state_tuple, feed_tuple):
            stacked = dict(zip(stacked_names, state_tuple[:n_stacked]))
            shared = dict(zip(shared_names, state_tuple[n_stacked:]))
            x_local = feed_tuple[0]
            ys_local = tuple(feed_tuple[1:])
            params_me = {t: stacked[t][0] for t in tmpl_params}
            loss, grads = sched(params_me, x_local, ys_local)
            if dp_axis is not None:
                loss = jax.lax.pmean(loss, dp_axis)
                if qctx is not None:
                    grads = {t: qctx.sync(t + GRAD_SUFFIX, g)
                             for t, g in grads.items()}
                else:
                    grads = {t: jax.lax.pmean(g, dp_axis)
                             for t, g in grads.items()}
            aux_vals = ()
            if tail_fn is not None:
                # aux fetches get EXACT serial semantics: gather the
                # pp-replicated chain output + label feeds to the full
                # batch on every dp shard, then run the unstamped tail
                # un-microbatched — every shard computes the identical
                # (replicated) value, scalar or per-row
                h = _gather_rows(fwd(params_me, x_local),
                                 feed_sharded[0])
                ys_full = tuple(
                    _gather_rows(y, sh)
                    for y, sh in zip(ys_local, feed_sharded[1:]))
                aux_vals = tail_fn(_unmicro(h),
                                   tuple(_unmicro(y) for y in ys_full))
            env = dict(shared)
            env.update({t: stacked[t][0] for t in stacked_names})
            env.update({t + GRAD_SUFFIX: grads[t] for t in tmpl_params})
            update(env)
            new_state = tuple(env[t][None] for t in stacked_names) \
                + tuple(env[n] for n in shared_names)
            fetches = tuple(
                loss if n == cut.loss_name
                else aux_vals[aux_names.index(n)] for n in fetch_names)
            return fetches, new_state

        stacked_spec = tuple(P("pp") for _ in stacked_names)
        shared_spec = tuple(P() for _ in shared_names)
        state_specs = stacked_spec + shared_spec
        fetch_specs = tuple(P() for _ in fetch_names)

        try:
            body = shard_map(local_step, mesh=mesh,
                             in_specs=(state_specs, feed_specs),
                             out_specs=(fetch_specs, state_specs),
                             check_rep=False)
        except TypeError:   # newer jax dropped check_rep
            body = shard_map(local_step, mesh=mesh,
                             in_specs=(state_specs, feed_specs),
                             out_specs=(fetch_specs, state_specs))

        def _finite(parts):
            flag = jnp.asarray(True)
            for v in parts:
                if jnp.issubdtype(jnp.result_type(v), jnp.inexact):
                    flag = jnp.logical_and(flag,
                                           jnp.all(jnp.isfinite(v)))
            return flag

        # The step's EXTERNAL state signature is flat per-stage
        # replicated vars (the scope layout every other path —
        # checkpoints, elastic shipping — already speaks); the stacking
        # onto the pp axis and the unstack back happen INSIDE the jit,
        # so no eager multi-device op ever races another host thread's
        # dispatch (concurrent eager gathers deadlock the CPU
        # backend's collective rendezvous), and a run_steps window
        # carries the pp-sharded stacked state across the whole scan
        # with zero boundary crossings.
        def _dstack(vals):
            # NOT jnp.stack: on this jax a concatenate feeding a
            # NESTED shard_map mis-partitions the operand (every shard
            # reads a blend instead of its P("pp") slice — repro: stack
            # two (8,8) into (2,8,8), pass through shard_map in jit).
            # dynamic_update_index_in_dim lowers to updates the SPMD
            # partitioner handles correctly.
            out = jnp.zeros((len(vals),) + tuple(vals[0].shape),
                            jnp.result_type(vals[0]))
            for i, v in enumerate(vals):
                out = jax.lax.dynamic_update_index_in_dim(
                    out, v.astype(out.dtype), i, 0)
            return out

        def _dstack_recut(vals):
            # re-cut geometry: (n_slots, k_per, ...) with row (j, i)
            # holding logical stage rec.stage_idx[j][i] (pads repeat the
            # slot's last real stage — never read back). Same
            # dynamic_update lowering as _dstack for the same
            # partitioner reason.
            shape = tuple(vals[0].shape)
            dt = jnp.result_type(vals[0])
            out = jnp.zeros((rec.n_slots, rec.k_per) + shape, dt)
            for j in range(rec.n_slots):
                for i in range(rec.k_per):
                    v = vals[rec.stage_idx[j][i]].astype(dt)
                    out = jax.lax.dynamic_update_slice(
                        out, v[None, None], (j, i) + (0,) * len(shape))
            return out

        stack_vals = _dstack if rec is None else _dstack_recut

        def _stack_in(state_tuple):
            stacked = tuple(
                stack_vals(state_tuple[i * n_stage:(i + 1) * n_stage])
                for i in range(n_stacked))
            return stacked + tuple(state_tuple[n_stacked * n_stage:])

        def _unstack_out(new_state):
            out = []
            for arr in new_state[:n_stacked]:
                if rec is None:
                    out.extend(arr[s] for s in range(n_stage))
                else:
                    out.extend(
                        arr[rec.slot_of[s],
                            s - rec.starts[rec.slot_of[s]]]
                        for s in range(n_stage))
            out.extend(new_state[n_stacked:])
            return tuple(out)

        if windowed:
            def target(state_tuple, feed_stack_tuple):
                def scan_body(carry, xs):
                    fetches, new_state = body(carry, xs)
                    ys = (fetches,)
                    if check_numerics:
                        ys += (_finite(list(fetches) + list(new_state)),)
                    return new_state, ys
                final_state, ys = jax.lax.scan(scan_body,
                                               _stack_in(state_tuple),
                                               feed_stack_tuple)
                return ys, _unstack_out(final_state)
        elif check_numerics:
            def target(state_tuple, feed_tuple):
                fetches, new_state = body(_stack_in(state_tuple),
                                          feed_tuple)
                return fetches, _unstack_out(new_state), \
                    _finite(list(fetches) + list(new_state))
        else:
            def target(state_tuple, feed_tuple):
                fetches, new_state = body(_stack_in(state_tuple),
                                          feed_tuple)
                return fetches, _unstack_out(new_state)

        n_flat = n_stacked * n_stage + len(shared_names)
        state_sh = tuple(NamedSharding(mesh, P()) for _ in range(n_flat))
        feed_sh = tuple(
            NamedSharding(mesh, P(*((None,) + tuple(s))))
            if windowed else NamedSharding(mesh, s)
            for s in feed_specs)
        if check_numerics and not windowed:
            out_sh = (None, state_sh, None)
        else:
            out_sh = (None, state_sh)
        run_step = self._wrap_sharded(target, mesh, state_sh, feed_sh,
                                      out_sh, window=windowed, qctx=qctx,
                                      pipeline=True)
        state_info = (tuple(stacked_names),
                      {t: tuple(cut.stage_state[t])
                       for t in stacked_names},
                      tuple(shared_names), tuple(feed_order))
        return state_info, run_step

    def _wrap_sharded(self, fn, mesh, state_sh, feed_sh, out_sh,
                      window=False, qctx="auto", pipeline=False):
        """Shared step/window machinery: jit over the mesh, stage inputs
        onto their shardings, and arm the one-behind collective-timeout
        watchdog. With quantize_collectives on, the fn is first lowered
        through shard_map with quantized gradient sync; the per-step wire
        accounting (static, accumulated at trace time) is recorded per
        dispatch (x window length for run_steps windows). With use_pallas
        set, the trace runs inside the Pallas dispatch scope so the wired
        op kernels route to their fused implementations.

        qctx: "auto" builds the QuantizedSyncContext here and wraps fn in
        the dp shard_map; a caller that already lowered its own shard_map
        (the pipeline path) passes its context — byte accounting and the
        watchdog still apply, the extra wrap does not."""
        if qctx == "auto":
            qctx = self._quantize_ctx(mesh)
            if qctx is not None:
                fn = self._quantized_fn(fn, mesh, state_sh, feed_sh,
                                        out_sh, qctx)
        pctx = self._pallas_ctx(mesh)
        if pctx is not None:
            from ..ops import pallas_dispatch as pd
            inner = fn

            def fn(state_tuple, feed_tuple, _inner=inner):
                # the scope only matters while jit TRACES _inner; entering
                # it per call is a few thread-local writes
                with pd.scope(pctx):
                    return _inner(state_tuple, feed_tuple)
        jitted = jax.jit(fn, in_shardings=(state_sh, feed_sh),
                         out_shardings=out_sh, donate_argnums=(0,))
        timeout_s = getattr(self._build_strategy, "collective_timeout_s",
                            None)
        # manual collectives on the CPU backend serialize process-wide
        # (see _MANUAL_COLLECTIVE_LOCK): any quantized or pipeline step
        # embeds shard_map ppermute/all_gather
        try:
            platform = next(iter(mesh.devices.flat)).platform
        except Exception:  # pragma: no cover - exotic mesh
            platform = jax.default_backend()
        serialize = (qctx is not None or pipeline) and platform == "cpu"
        pending = []  # previous call's outputs (one-behind watchdog)

        def run_step(state_vals, feed_tuple):
            with mesh:
                if timeout_s is not None and pending:
                    # Bound-wait on the PREVIOUS dispatch so async
                    # dispatch (host stages batch N+1 while the chip runs
                    # batch N) survives; a hung collective surfaces at
                    # the next call's entry — same one-step-late
                    # semantics as the reference's NCCL watchdog thread.
                    from .watchdog import wait_with_timeout
                    wait_with_timeout(
                        pending.pop(), timeout_s,
                        what="CompiledProgram step over mesh %r"
                        % (tuple(mesh.axis_names),))
                placed_state = tuple(
                    v if isinstance(v, jax.Array) and
                    getattr(v, "sharding", None) == s
                    else jax.device_put(v, s)
                    for v, s in zip(state_vals, state_sh))
                placed_feed = tuple(
                    _place_feed(v, s)
                    for v, s in zip(feed_tuple, feed_sh))
                if serialize:
                    # hold the lock through COMPLETION: a second
                    # thread's enqueue against a still-running manual
                    # collective is exactly the rendezvous interleaving
                    # that deadlocks the CPU backend
                    with _MANUAL_COLLECTIVE_LOCK:
                        out = jitted(placed_state, placed_feed)
                        jax.block_until_ready(out)
                else:
                    out = jitted(placed_state, placed_feed)
                if timeout_s is not None:
                    pending.append(out)
                if qctx is not None and qctx.raw_bytes:
                    # static per-step totals (populated by the first
                    # call's trace), multiplied by the window length:
                    # one record per dispatch, zero device syncs
                    from . import resilience
                    n = int(np.shape(feed_tuple[0])[0]) \
                        if window and feed_tuple else 1
                    # int-cast: merge-boundary syncs amortize bytes by
                    # 1/k, leaving fractional trace-time totals
                    resilience.record_bytes("collective",
                                            int(qctx.raw_bytes * n),
                                            int(qctx.wire_bytes * n))
                return out
        return run_step
