from .program import (Program, Block, Operator, Variable, Parameter,  # noqa
                      default_main_program, default_startup_program,
                      program_guard, name_scope, switch_main_program,
                      switch_startup_program, grad_var_name)
from .place import TPUPlace, CPUPlace, _current_expected_place  # noqa
from .scope import Scope, global_scope, scope_guard  # noqa
from .executor import Executor  # noqa
from .backward import append_backward, gradients  # noqa
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa
from . import unique_name  # noqa
from . import watchdog  # noqa
from . import obs  # noqa
from . import resilience  # noqa
from . import coordination  # noqa
from . import transport  # noqa
from .watchdog import (CollectiveTimeoutError, wait_with_timeout,  # noqa
                       StragglerDetector)
from .resilience import (FaultInjector, RetryPolicy,  # noqa
                         ResilientTrainer, SimulatedPreemptionError,
                         ServerOverloadedError, DeadlineExceededError,
                         RestartBudgetExceededError)
from .coordination import (Coordinator, LocalCoordinator,  # noqa
                           FileCoordinator, SocketCoordinator,
                           PodResilientTrainer,
                           CoordinationError, HostLostError,
                           NoQuorumError)
from .transport import (CoordServer, CoordClient, TransportError,  # noqa
                        replicated_group)
