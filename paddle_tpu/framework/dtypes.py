"""Dtype system for paddle_tpu.

Reference parity: paddle/fluid/framework/data_type.h (proto VarType dtypes).
TPU-first: bfloat16 is first-class; fp64 is supported but discouraged (TPUs
emulate it slowly), so layers default to float32/bfloat16.
"""
import numpy as np
import jax.numpy as jnp

# Canonical dtype names -> jnp dtypes.
_STR2DTYPE = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "int": "int32",
    "long": "int64",
    "half": "float16",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
INT_DTYPES = ("int8", "uint8", "int16", "int32", "int64")


def normalize_dtype(dtype):
    """Return the canonical string name for *dtype* (str, np dtype or jnp dtype)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name not in _STR2DTYPE:
            raise TypeError("unsupported dtype string: %r" % (dtype,))
        return name
    # numpy / jax dtype objects and python types
    name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    name = _ALIASES.get(name, name)
    if name not in _STR2DTYPE:
        raise TypeError("unsupported dtype: %r" % (dtype,))
    return name


def to_jax_dtype(dtype):
    return _STR2DTYPE[normalize_dtype(dtype)]


def dtype_size(dtype):
    """Bytes per element of *dtype* (bfloat16 -> 2)."""
    name = normalize_dtype(dtype)
    if name == "bfloat16":
        return 2
    return np.dtype(name).itemsize


def is_float(dtype):
    return normalize_dtype(dtype) in FLOAT_DTYPES


def is_integer(dtype):
    return normalize_dtype(dtype) in INT_DTYPES
