# Copyright (c) 2026 PaddlePaddle-on-JAX growth authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
"""In-memory buddy checkpointing: sub-window recovery without disk rewind.

Every disk-rewind recovery path (pod consensus rewind, numeric-fault
rewind, the infeasible-re-cut fallback) loses up to a full checkpoint
interval of work plus a cold disk restore — for the MOST COMMON fault,
a single host loss. This module keeps a warm replica of each host's
scope one hop away instead:

* **Ring.** ``buddy(i) = next live host after i`` on the sorted frozen
  membership (``ring_buddies``). Deterministic from the same frozen
  verdicts every host already agrees on, re-derived on every elastic
  resize/re-cut — no extra coordination.
* **Send.** At each committed window boundary every host encodes its
  scope with the CHECKPOINT codec (:func:`io.encode_state_blob` —
  zlib default is bitwise-lossless, q8 opt-in rides
  ``ops/quant_ops``) and ships it to the coordination plane via
  ``put_blob``, stamped with the boundary step as its *generation*.
  The server keeps ONE generation per owner (bounded memory) and
  refuses generation rewinds, so a delayed put can never clobber what
  a restore may already have adopted. Send failures NEVER fail
  training — the previous generation simply stays restorable.
* **Restore.** On a fault the pod first tries the buddy tier: every
  live host polls mailbox METADATA for the owners it needs, computes
  the same typed verdict, and one gather agrees it pod-wide
  (conservative merge — any host's doubt falls everyone back to the
  disk rewind with a typed reason: ``buddy_missing``,
  ``buddy_stale``, ``buddy_and_host_lost``). When agreed, each host
  fetches and DECODES its own snapshot without touching its scope,
  a second gather confirms every decode, and only then does anyone
  adopt — a torn snapshot (``snapshot_torn``) can never leave the pod
  half-restored. A buddy restore loses at most one window and is
  bitwise equal to the uninterrupted reference (zlib codec).

The mailbox rides the existing CoordServer wire: synchronously
replicated to standbys and snapshot-covered, so an acked snapshot
survives coordinator failover. FileCoordinator pods have no shared
mailbox (the base store is per-process) — every restore attempt there
consistently reports ``buddy_missing`` and takes the disk rewind,
which is the documented degradation, not an error.
"""

from __future__ import print_function

import time

import numpy as np

from . import faultinject, obs, resilience
from .resilience import record_event

__all__ = ["ring_buddies", "buddy_of", "send_snapshot", "plan_restore",
           "agree_plan", "restore_agreed", "fetch_and_decode",
           "adopt_arrays", "FALLBACK_REASONS"]

# typed disk-fallback reasons, in conservative-merge precedence order:
# when hosts disagree (e.g. a racing eviction made one host see a miss
# where another saw the double loss), the pod adopts the FIRST reason
# by this ranking so every host records the same label
FALLBACK_REASONS = ("buddy_and_host_lost", "buddy_missing",
                    "buddy_stale", "snapshot_torn")


# -- ring assignment --------------------------------------------------------
def ring_buddies(members):
    """``{host: buddy}`` over the sorted membership ring —
    ``buddy(i) = (i+1) % n`` in ring position, so every host has
    exactly one buddy and is exactly one host's buddy. Empty for
    fewer than two members (a ring of one would buddy a host to
    itself, which replicates nothing)."""
    ring = sorted({int(m) for m in members})
    if len(ring) < 2:
        return {}
    return {h: ring[(i + 1) % len(ring)] for i, h in enumerate(ring)}


def buddy_of(host, members):
    """``host``'s buddy under ``members``' ring, or None."""
    return ring_buddies(members).get(int(host))


# -- window-boundary send ---------------------------------------------------
def send_snapshot(co, host_id, members, gen, scope, compress="zlib",
                  feed=None, reset=False):
    """Encode this host's scope (+ feed cursor) and mail it to the
    coordination plane under generation ``gen``.

    A send failure NEVER fails training: any exception (including the
    catalogued ``buddy.send`` failpoint and a coordinator outage) is
    swallowed into a ``buddy_send_fail`` event and the mailbox keeps
    the PREVIOUS generation, still restorable. Returns True when the
    snapshot landed. Skipped (False) for rings of fewer than two
    members — there is no peer RAM to replicate into."""
    from .. import io as io_mod
    hid, gen = int(host_id), int(gen)
    buds = ring_buddies(members)
    if hid not in buds:
        return False
    try:
        with obs.span("buddy.send", host=hid, gen=gen,
                      buddy=buds[hid]):
            arrays = {}
            for name, val in sorted(scope.items()):
                if val is None:
                    continue
                arrays[name] = np.asarray(val)
            feed_state = None if feed is None else feed.global_state()
            # the failpoint fires BEFORE the put: a fault mid-send
            # must leave the server holding the previous generation
            faultinject.hit("buddy.send", {"gen": gen}, host=hid)
            blob, raw, wire = io_mod.encode_state_blob(
                arrays, gen, compress=compress, feed_state=feed_state)
            co.put_blob(hid, gen, buds[hid], blob, reset=reset)
        resilience.record_bytes("buddy_snapshot", raw, wire)
        resilience.record_buddy_gen(hid, gen)
        return True
    except Exception as e:
        record_event("buddy_send_fail", host=hid, gen=gen,
                     error=type(e).__name__)
        return False


# -- restore: verdict, agreement, adoption ----------------------------------
def plan_restore(co, live, lost, prev_members, expected_gen):
    """This host's LOCAL buddy-restore verdict from mailbox metadata
    only (no payload fetched): None when a buddy restore at
    ``expected_gen`` looks possible, else the typed fallback reason.

    ``prev_members`` is the membership the last sends were ringed
    over (live + the hosts lost THIS round): a lost owner whose buddy
    under that ring is also gone means the replica's RAM died with it
    (``buddy_and_host_lost``). Every owner — live and lost — must
    hold exactly ``expected_gen``: an absent mailbox is
    ``buddy_missing``, any other generation ``buddy_stale``."""
    lost = sorted({int(h) for h in lost})
    owners = sorted({int(h) for h in live} | set(lost))
    buds = ring_buddies(prev_members)
    for o in lost:
        b = buds.get(o)
        if b is None or b in lost:
            return "buddy_and_host_lost"
    for o in owners:
        try:
            meta = co.get_blob(o, meta_only=True)
        except Exception:
            meta = None
        if meta is None:
            return "buddy_missing"
        if int(meta["gen"]) != int(expected_gen):
            return "buddy_stale"
    return None


def agree_plan(co, hid, name, live, lost, prev_members, expected_gen):
    """Pod-wide buddy-restore election (gather #1): every live host
    publishes its local :func:`plan_restore` verdict and the frozen
    gather merges them CONSERVATIVELY — any host's doubt falls the
    whole pod back, under the first reason by
    :data:`FALLBACK_REASONS` precedence so every host records the
    same label. Returns None (agreed: restore at ``expected_gen``)
    or the agreed reason."""
    local = plan_restore(co, live, lost, prev_members, expected_gen)
    verd = co.all_gather(name + "v", hid,
                         "ok" if local is None else local)
    reasons = [r for r in verd.values() if r != "ok"]
    if not reasons:
        return None
    rank = {r: i for i, r in enumerate(FALLBACK_REASONS)}
    return min(reasons, key=lambda r: (rank.get(r, len(rank)), r))


def fetch_and_decode(co, host_id, gen, need_feed_state=False):
    """Pull THIS host's snapshot payload and decode it to host arrays
    WITHOUT touching the scope. Raises on any tear: a moved
    generation, a decode failure, a missing cursor when the caller
    needs one — the caller treats every raise as ``snapshot_torn``.
    The catalogued ``buddy.restore`` failpoint fires between fetch
    and decode."""
    from .. import io as io_mod
    hid, gen = int(host_id), int(gen)
    rec = co.get_blob(hid)
    if rec is None:
        raise LookupError("no buddy snapshot for host %d" % hid)
    if int(rec["gen"]) != gen:
        raise LookupError(
            "buddy snapshot for host %d moved to gen %d while "
            "restoring gen %d" % (hid, int(rec["gen"]), gen))
    faultinject.hit("buddy.restore", {"gen": gen}, host=hid)
    arrays, got, feed_state = io_mod.decode_state_blob(rec["blob"])
    if int(got) != gen:
        raise ValueError(
            "buddy snapshot for host %d carries step %d inside a "
            "gen-%d mailbox" % (hid, int(got), gen))
    if need_feed_state and feed_state is None:
        raise ValueError(
            "buddy snapshot for host %d has no feed cursor but the "
            "trainer drives a ShardedFeed" % hid)
    return arrays, feed_state


def adopt_arrays(scope, arrays, shardings=None):
    """Install decoded host arrays into the scope, re-sharding each
    device value onto ``shardings`` (or its CURRENT sharding when the
    map has no entry — the unchanged-mesh case). Only called after
    the pod agreed every host's decode succeeded."""
    import jax
    for name, host_arr in sorted(arrays.items()):
        sh = None if shardings is None else shardings.get(name)
        if sh is None:
            cur = scope.find_var(name)
            if isinstance(cur, jax.Array):
                sh = cur.sharding
        scope.set_var(name, host_arr if sh is None
                      else jax.device_put(host_arr, sh))


def restore_agreed(co, hid, name, gen, scope, shardings=None,
                   need_feed_state=False):
    """Stage 2, after :func:`agree_plan` said ok: fetch + decode this
    host's snapshot (scope untouched), agree every host's decode
    outcome on gather #2, and only then adopt. Returns
    ``(True, feed_state)`` on success, ``(False, None)`` when any
    host's decode tore — nobody adopted anything, the caller takes
    the disk rewind with ``snapshot_torn``."""
    t0 = time.perf_counter()
    ok, arrays, feed_state = True, None, None
    try:
        with obs.span("buddy.restore", host=int(hid), gen=int(gen)):
            arrays, feed_state = fetch_and_decode(
                co, hid, gen, need_feed_state=need_feed_state)
    except Exception as e:
        ok = False
        record_event("buddy_decode_fail", host=int(hid), gen=int(gen),
                     error=type(e).__name__)
    outs = co.all_gather(name + "d", hid, bool(ok))
    if not all(outs.values()):
        return False, None
    adopt_arrays(scope, arrays, shardings=shardings)
    record_event("buddy_adopt", host=int(hid), gen=int(gen),
                 latency_s=round(time.perf_counter() - t0, 6))
    return True, feed_state
