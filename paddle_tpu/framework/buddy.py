# Copyright (c) 2026 PaddlePaddle-on-JAX growth authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
"""Peer-to-peer buddy checkpointing: warm recovery without a memory SPOF.

Every disk-rewind recovery path (pod consensus rewind, numeric-fault
rewind, the infeasible-re-cut fallback) loses up to a full checkpoint
interval of work plus a cold disk restore — for the MOST COMMON fault,
a single host loss. This module keeps a warm replica of each host's
scope one hop away instead, with the PAYLOAD resident in peer host
RAM and the coordinator holding only a metadata table:

* **Ring.** ``buddy(i) = next live host after i`` on the sorted frozen
  membership (``ring_buddies``). Deterministic from the same frozen
  verdicts every host already agrees on, re-derived on every elastic
  resize/re-cut — no extra coordination.
* **Mailboxes.** Every host runs a small :class:`BuddyMailbox` (over
  the socket plane, a ``transport.MailboxServer`` endpoint on the
  CoordServer newline-JSON wire). At each committed window boundary
  host *i* deposits its encoded scope into its OWN mailbox (the warm
  replica a restart of *i* itself re-adopts without crossing the
  wire) and streams it into ring buddy *i+1*'s mailbox (the replica
  that survives *i*'s death). Each mailbox slot holds exactly ONE
  reconstructible generation per owner; generation rewinds are
  refused, so a delayed deposit can never clobber what a restore may
  already have adopted.
* **Ack-before-commit.** Only after the buddy's mailbox ACKS the
  deposit does the sender publish the ``{host: (gen, buddy, digest,
  nbytes)}`` row to the coordinator (``put_buddy_meta`` — replicated
  and snapshot-covered, but METADATA-sized: the coordinator memory
  ceiling of the put_blob era is gone). A stream torn mid-send leaves
  the metadata row at the previous generation, so a torn payload can
  never be elected.
* **Deltas.** With a sender-side :class:`DeltaTracker`, a boundary
  send ships only the leaves whose content digest changed since the
  last acked generation (optimizer moments churn; embeddings mostly
  don't), as one link of a bounded per-slot delta chain over the last
  full snapshot, re-based to a forced full every ``rebase_every``
  sends. A receiver that cannot extend its chain refuses typed
  (``delta_chain_broken`` / ``digest_mismatch``) and the sender falls
  back to a forced full — never a silent divergence. Deltas require a
  bitwise codec (zlib/None); q8 sends are always full and unverified.
* **Restore.** On a fault the pod first tries the buddy tier: every
  live host plans from coordinator METADATA only (no payload moves),
  and one gather agrees the verdict pod-wide (conservative merge —
  any host's doubt falls everyone back to the disk rewind with a
  typed reason: ``buddy_missing``, ``buddy_stale``,
  ``buddy_and_host_lost``). When agreed, each host pulls its own
  snapshot — local mailbox first, host-to-host from its buddy's
  mailbox on a local miss — decodes it WITHOUT touching its scope and
  verifies the state digest against the coordinator row; a second
  gather confirms every decode, and only then does anyone adopt. A
  torn stream, a broken chain or a digest mismatch all land in
  ``snapshot_torn`` (nobody adopts, disk rewind); a buddy restore
  loses at most one window and is bitwise equal to the uninterrupted
  reference (zlib codec).

The legacy coordinator-mailbox mode (``p2p=False``: payloads ride
``put_blob`` onto the coordination plane) stays for pods whose hosts
cannot reach each other directly, now bounded by the coordinator's
``blob_max_bytes`` ceiling. FileCoordinator pods have no shared
mailbox plane (the base registry is per-process) — every restore
attempt there consistently reports ``buddy_missing`` and takes the
disk rewind, which is the documented degradation, not an error.
"""

from __future__ import print_function

import threading
import time

import numpy as np

from . import faultinject, obs, resilience
from .resilience import record_event

__all__ = ["ring_buddies", "buddy_of", "send_snapshot", "plan_restore",
           "agree_plan", "restore_agreed", "fetch_and_decode",
           "adopt_arrays", "FALLBACK_REASONS", "DELTA_REFUSALS",
           "BuddyMailbox", "DeltaTracker"]

# typed disk-fallback reasons, in conservative-merge precedence order:
# when hosts disagree (e.g. a racing eviction made one host see a miss
# where another saw the double loss), the pod adopts the FIRST reason
# by this ranking so every host records the same label
FALLBACK_REASONS = ("buddy_and_host_lost", "buddy_missing",
                    "buddy_stale", "snapshot_torn")

# typed mailbox-deposit refusals that force the sender's NEXT attempt
# to a full snapshot (the receiver's chain state cannot extend)
DELTA_REFUSALS = ("delta_chain_broken", "digest_mismatch")

# compress modes whose decode is bitwise (deltas and digest
# verification are only sound over a lossless codec; q8 is lossy)
_BITWISE_COMPRESS = (None, "zlib")


# -- ring assignment --------------------------------------------------------
def ring_buddies(members):
    """``{host: buddy}`` over the sorted membership ring —
    ``buddy(i) = (i+1) % n`` in ring position, so every host has
    exactly one buddy and is exactly one host's buddy. Empty for
    fewer than two members (a ring of one would buddy a host to
    itself, which replicates nothing)."""
    ring = sorted({int(m) for m in members})
    if len(ring) < 2:
        return {}
    return {h: ring[(i + 1) % len(ring)] for i, h in enumerate(ring)}


def buddy_of(host, members):
    """``host``'s buddy under ``members``' ring, or None."""
    return ring_buddies(members).get(int(host))


# -- mailbox (one per host; payloads live HERE, not on the coordinator) -----
def _payload_wire_bytes(payload):
    """Resident size of one deposited payload: the base64 npz text is
    the dominant term (the JSON envelope is noise)."""
    blob = payload.get("blob") or {}
    return len(blob.get("npz", ""))


class BuddyMailbox(object):
    """One host's in-RAM buddy mailbox: ``{owner: slot}`` where a slot
    is the owner's last FULL snapshot plus a bounded chain of delta
    payloads that reconstruct exactly ONE generation. Thread-safe (the
    socket endpoint serves deposits and fetches concurrently).

    Deposit semantics mirror the coordinator's legacy blob fence:
    generation rewinds are refused (``reset=True`` on a full deposit
    bypasses, for post-restore re-seeds), an equal-generation full
    deposit replaces (idempotent resend / forced-full correction), and
    a delta must name the exact ``(prev_gen, prev_digest)`` the slot
    currently reconstructs to — anything else is a typed refusal, not
    an exception."""

    def __init__(self, host_id=None, max_chain=64):
        self._host = None if host_id is None else int(host_id)
        self._max_chain = max(1, int(max_chain))
        self._slots = {}
        self._lock = threading.RLock()

    @property
    def host_id(self):
        return self._host

    def _record_resident_locked(self):
        if self._host is not None:
            resilience.record_buddy_resident(
                self._host, self._resident_bytes_locked())

    def _resident_bytes_locked(self):
        return sum(s["nbytes"] for s in self._slots.values())

    def resident_bytes(self):
        """Total payload bytes resident across all slots."""
        with self._lock:
            return self._resident_bytes_locked()

    def owners(self):
        with self._lock:
            return sorted(self._slots)

    def meta(self, owner=None):
        """Metadata view (no payloads): one owner's ``{gen, digest,
        nbytes, chain_len}`` (or None), or all owners' when ``owner``
        is None."""
        with self._lock:
            if owner is not None:
                s = self._slots.get(int(owner))
                return None if s is None else self._meta_of(s)
            return {o: self._meta_of(s) for o, s in self._slots.items()}

    @staticmethod
    def _meta_of(s):
        return {"gen": s["gen"], "digest": s["digest"],
                "nbytes": s["nbytes"], "chain_len": len(s["chain"])}

    def drop(self, owner):
        """Evict one owner's slot (membership shrink / double loss)."""
        with self._lock:
            self._slots.pop(int(owner), None)
            self._record_resident_locked()

    def clear(self):
        with self._lock:
            self._slots.clear()
            self._record_resident_locked()

    def deposit(self, owner, payload):
        """Apply one deposited payload; returns an ack dict —
        ``{"ok": True, "gen", "digest", "nbytes", "chain_len"}`` — or
        a typed refusal ``{"ok": False, "refused": reason}``. Protocol
        refusals never raise; only a malformed payload does."""
        owner = int(owner)
        kind = payload.get("kind")
        if kind not in ("full", "delta"):
            raise ValueError("mailbox deposit kind must be full|delta, "
                             "got %r" % (kind,))
        gen = int(payload["gen"])
        nb = _payload_wire_bytes(payload)
        with self._lock:
            slot = self._slots.get(owner)
            if kind == "full":
                if slot is not None and gen < slot["gen"] \
                        and not payload.get("reset"):
                    return {"ok": False, "refused": "gen_rewind",
                            "gen": slot["gen"]}
                self._slots[owner] = {
                    "gen": gen, "digest": payload.get("digest"),
                    "base": payload["blob"], "chain": [], "nbytes": nb}
            else:
                if slot is None \
                        or int(payload["prev_gen"]) != slot["gen"] \
                        or len(slot["chain"]) >= self._max_chain:
                    return {"ok": False, "refused": "delta_chain_broken",
                            "gen": None if slot is None else slot["gen"]}
                if payload.get("prev_digest") != slot["digest"]:
                    return {"ok": False, "refused": "digest_mismatch",
                            "gen": slot["gen"]}
                if gen <= slot["gen"]:
                    return {"ok": False, "refused": "gen_rewind",
                            "gen": slot["gen"]}
                slot["chain"].append(
                    {"gen": gen, "digest": payload.get("digest"),
                     "blob": payload["blob"],
                     "removed": list(payload.get("removed") or ())})
                slot["gen"] = gen
                slot["digest"] = payload.get("digest")
                slot["nbytes"] += nb
            s = self._slots[owner]
            self._record_resident_locked()
            ack = {"ok": True}
            ack.update(self._meta_of(s))
            return ack

    def reconstruct(self, owner):
        """Reconstruct ``owner``'s single resident generation to one
        full wire record ``{gen, digest, blob}``. The chainless common
        case returns the deposited full blob untouched; a chained slot
        decodes the base, applies each delta link (the catalogued
        ``buddy.delta_apply`` failpoint fires per link), verifies the
        reconstructed state digest against the slot's, and re-encodes.
        Raises LookupError on a missing slot and ValueError on any
        chain/digest corruption — the fetching side treats every raise
        as ``snapshot_torn``."""
        from .. import io as io_mod
        with self._lock:
            slot = self._slots.get(int(owner))
            if slot is None:
                raise LookupError(
                    "no mailbox slot for owner %s" % (owner,))
            gen, digest = slot["gen"], slot["digest"]
            base, chain = slot["base"], list(slot["chain"])
        if not chain:
            return {"gen": gen, "digest": digest, "blob": base}
        arrays, step, feed_state = io_mod.decode_state_blob(base)
        compress = base.get("compress")
        for link in chain:
            faultinject.hit("buddy.delta_apply",
                            {"owner": int(owner), "gen": link["gen"]},
                            host=self._host)
            darr, dstep, dfeed = io_mod.decode_state_blob(link["blob"])
            if int(dstep) != int(link["gen"]):
                raise ValueError(
                    "delta link for owner %s carries step %d inside a "
                    "gen-%d link" % (owner, int(dstep), int(link["gen"])))
            for name in link["removed"]:
                arrays.pop(name, None)
            arrays.update(darr)
            if dfeed is not None:
                feed_state = dfeed
            step = dstep
        if digest is not None \
                and io_mod.state_digest(arrays) != digest:
            raise ValueError(
                "mailbox chain for owner %s reconstructs to a state "
                "that fails digest verification at gen %d"
                % (owner, gen))
        blob, _, _ = io_mod.encode_state_blob(
            arrays, gen, compress=compress, feed_state=feed_state)
        return {"gen": gen, "digest": digest, "blob": blob}


# -- sender-side delta state ------------------------------------------------
class DeltaTracker(object):
    """Per-host sender state for delta snapshots: the last ACKED
    generation/digest, per-leaf content digests (the skip test), the
    chain length since the last full send (re-based to a forced full
    every ``rebase_every`` sends) and the last full send's wire bytes
    (the ``buddy_delta_ratio`` denominator). Reset forces the next
    send full — the safe answer whenever the receiver's chain state is
    unknown (after a failed send, a restore, or a re-seed)."""

    def __init__(self, rebase_every=8):
        self.rebase_every = max(1, int(rebase_every))
        self.reset()

    def reset(self):
        self.gen = None
        self.digest = None
        self.leaves = {}
        self.chain_len = 0
        self.full_wire = None


# -- window-boundary send ---------------------------------------------------
def _encode_payload(io_mod, arrays, gen, compress, feed_state,
                    tracker, reset, force_full):
    """Encode one boundary send as a full or delta payload. Returns
    ``(payload, raw_bytes, wire_bytes, leaf_digests, kind)`` — raw is
    always the FULL scope's bytes (what the uncompressed path would
    have moved), so the bytes accounting shows what deltas saved."""
    bitwise = compress in _BITWISE_COMPRESS
    digests = io_mod.leaf_digests(arrays) if bitwise else None
    digest = io_mod.state_digest(arrays) if bitwise else None
    raw_full = sum(int(a.nbytes) for a in arrays.values())
    if bitwise and not reset and not force_full and tracker is not None \
            and tracker.gen is not None \
            and tracker.chain_len < tracker.rebase_every:
        changed = {n: a for n, a in arrays.items()
                   if digests[n] != tracker.leaves.get(n)}
        removed = sorted(set(tracker.leaves) - set(arrays))
        blob, _, wire = io_mod.encode_state_blob(
            changed, gen, compress=compress, feed_state=feed_state)
        return ({"kind": "delta", "gen": gen,
                 "prev_gen": tracker.gen,
                 "prev_digest": tracker.digest,
                 "digest": digest, "removed": removed, "blob": blob},
                raw_full, wire, digests, "delta")
    blob, _, wire = io_mod.encode_state_blob(
        arrays, gen, compress=compress, feed_state=feed_state)
    payload = {"kind": "full", "gen": gen, "digest": digest,
               "blob": blob}
    if reset:
        payload["reset"] = True
    return payload, raw_full, wire, digests, "full"


def _deposit_dual(co, hid, bud, payload):
    """Deposit one payload into the owner's OWN mailbox first (the
    free local replica) and then stream it to the ring buddy's (the
    one that survives the owner's death). Returns ``(buddy_ack,
    refused_reason)`` — exactly one is non-None. The catalogued
    ``buddy.p2p_send`` failpoint fires between the two, modelling a
    stream torn on the wire after the local deposit landed."""
    self_ack = co.mailbox_send(hid, hid, payload)
    if not self_ack.get("ok"):
        return None, self_ack.get("refused", "refused")
    faultinject.hit("buddy.p2p_send",
                    {"gen": payload["gen"], "buddy": bud}, host=hid)
    ack = co.mailbox_send(hid, bud, payload)
    if not ack.get("ok"):
        return None, ack.get("refused", "refused")
    return ack, None


def send_snapshot(co, host_id, members, gen, scope, compress="zlib",
                  feed=None, reset=False, p2p=True, tracker=None):
    """Encode this host's scope (+ feed cursor) and replicate it under
    generation ``gen`` — p2p (default): deposit into the own + ring
    buddy mailboxes, then publish the metadata row to the coordinator
    ONLY after the buddy acked (ack-before-commit); legacy
    (``p2p=False``): ``put_blob`` the payload onto the coordination
    plane as before.

    With a :class:`DeltaTracker` the p2p payload is a per-leaf delta
    when possible; a typed receiver refusal falls back to ONE forced
    full in the same call. A send failure NEVER fails training: any
    exception (including the catalogued ``buddy.send``/
    ``buddy.p2p_send`` failpoints and a coordinator outage) is
    swallowed into a ``buddy_send_fail`` event, the metadata row keeps
    the PREVIOUS generation (still restorable) and the tracker resets
    so the next attempt is full. Returns True when the snapshot
    committed. Skipped (False) for rings of fewer than two members —
    there is no peer RAM to replicate into."""
    from .. import io as io_mod
    hid, gen = int(host_id), int(gen)
    buds = ring_buddies(members)
    if hid not in buds:
        return False
    try:
        with obs.span("buddy.send", host=hid, gen=gen,
                      buddy=buds[hid]):
            arrays = {}
            for name, val in sorted(scope.items()):
                if val is None:
                    continue
                arrays[name] = np.asarray(val)
            feed_state = None if feed is None else feed.global_state()
            # the failpoint fires BEFORE any deposit: a fault mid-send
            # must leave the previous generation committed
            faultinject.hit("buddy.send", {"gen": gen}, host=hid)
            if not p2p:
                blob, raw, wire = io_mod.encode_state_blob(
                    arrays, gen, compress=compress,
                    feed_state=feed_state)
                co.put_blob(hid, gen, buds[hid], blob, reset=reset)
                kind, digests, ack = "full", None, None
            else:
                payload, raw, wire, digests, kind = _encode_payload(
                    io_mod, arrays, gen, compress, feed_state,
                    tracker, reset, force_full=False)
                ack, refused = _deposit_dual(co, hid, buds[hid],
                                             payload)
                if ack is None and kind == "delta" \
                        and refused in DELTA_REFUSALS:
                    # the receiver cannot extend its chain — typed
                    # fallback to ONE forced full, same boundary
                    record_event("buddy_delta_refused", host=hid,
                                 gen=gen, reason=refused)
                    payload, raw, wire, digests, kind = \
                        _encode_payload(io_mod, arrays, gen, compress,
                                        feed_state, tracker, reset,
                                        force_full=True)
                    ack, refused = _deposit_dual(co, hid, buds[hid],
                                                 payload)
                if ack is None:
                    raise ConnectionError(
                        "buddy mailbox refused deposit: %s" % refused)
                # ack-before-commit: the metadata row moves only now
                co.put_buddy_meta(hid, gen, buds[hid],
                                  payload.get("digest"),
                                  int(ack.get("nbytes", wire)),
                                  reset=reset)
        resilience.record_bytes("buddy_snapshot", raw, wire)
        resilience.record_buddy_gen(hid, gen)
        if p2p and tracker is not None:
            tracker.gen = gen
            tracker.digest = payload.get("digest")
            tracker.leaves = digests or {}
            if kind == "full":
                tracker.chain_len, tracker.full_wire = 0, wire
            else:
                tracker.chain_len += 1
            if tracker.full_wire:
                resilience.record_buddy_delta_ratio(
                    round(float(wire) / float(tracker.full_wire), 6))
        return True
    except Exception as e:
        record_event("buddy_send_fail", host=hid, gen=gen,
                     error=type(e).__name__)
        if tracker is not None:
            tracker.reset()
        return False


# -- restore: verdict, agreement, adoption ----------------------------------
def plan_restore(co, live, lost, prev_members, expected_gen, p2p=True):
    """This host's LOCAL buddy-restore verdict from coordinator
    metadata only (no payload moves): None when a buddy restore at
    ``expected_gen`` looks possible, else the typed fallback reason.

    ``prev_members`` is the membership the last sends were ringed
    over (live + the hosts lost THIS round): a lost owner whose buddy
    under that ring is also gone means the replica's RAM died with it
    (``buddy_and_host_lost``) — in p2p mode the metadata row's
    RECORDED buddy is checked too, in case the last committed send
    pre-dated a membership change. Every owner — live and lost — must
    hold exactly ``expected_gen``: an absent row is ``buddy_missing``,
    any other generation ``buddy_stale``."""
    lost = sorted({int(h) for h in lost})
    owners = sorted({int(h) for h in live} | set(lost))
    buds = ring_buddies(prev_members)
    for o in lost:
        b = buds.get(o)
        if b is None or b in lost:
            return "buddy_and_host_lost"
    for o in owners:
        try:
            meta = co.buddy_meta(o) if p2p \
                else co.get_blob(o, meta_only=True)
        except Exception:
            meta = None
        if meta is None:
            return "buddy_missing"
        if int(meta["gen"]) != int(expected_gen):
            return "buddy_stale"
        if p2p and o in lost and int(meta.get("buddy", -1)) in lost:
            return "buddy_and_host_lost"
    return None


def agree_plan(co, hid, name, live, lost, prev_members, expected_gen,
               p2p=True):
    """Pod-wide buddy-restore election (gather #1): every live host
    publishes its local :func:`plan_restore` verdict and the frozen
    gather merges them CONSERVATIVELY — any host's doubt falls the
    whole pod back, under the first reason by
    :data:`FALLBACK_REASONS` precedence so every host records the
    same label. Returns None (agreed: restore at ``expected_gen``)
    or the agreed reason."""
    local = plan_restore(co, live, lost, prev_members, expected_gen,
                         p2p=p2p)
    verd = co.all_gather(name + "v", hid,
                         "ok" if local is None else local)
    reasons = [r for r in verd.values() if r != "ok"]
    if not reasons:
        return None
    rank = {r: i for i, r in enumerate(FALLBACK_REASONS)}
    return min(reasons, key=lambda r: (rank.get(r, len(rank)), r))


def fetch_and_decode(co, host_id, gen, need_feed_state=False,
                     p2p=True):
    """Pull THIS host's snapshot payload and decode it to host arrays
    WITHOUT touching the scope. P2p pulls local-mailbox-first, then
    host-to-host from the metadata row's recorded buddy (the
    catalogued ``buddy.p2p_fetch`` failpoint fires before the remote
    hop; its latency lands in the ``buddy_p2p_fetch_ms`` gauge), and
    verifies the decoded state's digest against the coordinator row.
    Raises on any tear: a moved generation, a decode or digest
    failure, a missing cursor when the caller needs one — the caller
    treats every raise as ``snapshot_torn``. The catalogued
    ``buddy.restore`` failpoint fires between fetch and decode."""
    from .. import io as io_mod
    hid, gen = int(host_id), int(gen)
    meta = None
    if p2p:
        meta = co.buddy_meta(hid)
        if meta is None:
            raise LookupError("no buddy metadata for host %d" % hid)
        if int(meta["gen"]) != gen:
            raise LookupError(
                "buddy metadata for host %d moved to gen %d while "
                "restoring gen %d" % (hid, int(meta["gen"]), gen))
        try:
            rec = co.mailbox_fetch(hid, hid)
        except Exception:
            rec = None
        if rec is None or int(rec["gen"]) != gen:
            # local replica gone (host restarted) or already advanced
            # past the agreed generation — pull host-to-host from the
            # buddy's mailbox
            faultinject.hit("buddy.p2p_fetch",
                            {"gen": gen, "buddy": meta["buddy"]},
                            host=hid)
            t0 = time.perf_counter()
            rec = co.mailbox_fetch(hid, int(meta["buddy"]))
            resilience.record_buddy_fetch_ms(
                round((time.perf_counter() - t0) * 1e3, 3))
        if rec is None:
            raise LookupError(
                "no buddy mailbox payload for host %d" % hid)
        if int(rec["gen"]) != gen:
            raise LookupError(
                "buddy mailbox for host %d holds gen %d while "
                "restoring gen %d" % (hid, int(rec["gen"]), gen))
    else:
        rec = co.get_blob(hid)
        if rec is None:
            raise LookupError("no buddy snapshot for host %d" % hid)
        if int(rec["gen"]) != gen:
            raise LookupError(
                "buddy snapshot for host %d moved to gen %d while "
                "restoring gen %d" % (hid, int(rec["gen"]), gen))
    faultinject.hit("buddy.restore", {"gen": gen}, host=hid)
    arrays, got, feed_state = io_mod.decode_state_blob(rec["blob"])
    if int(got) != gen:
        raise ValueError(
            "buddy snapshot for host %d carries step %d inside a "
            "gen-%d mailbox" % (hid, int(got), gen))
    if p2p and meta.get("digest") is not None \
            and io_mod.state_digest(arrays) != meta["digest"]:
        raise ValueError(
            "buddy snapshot for host %d fails digest verification "
            "at gen %d" % (hid, gen))
    if need_feed_state and feed_state is None:
        raise ValueError(
            "buddy snapshot for host %d has no feed cursor but the "
            "trainer drives a ShardedFeed" % hid)
    return arrays, feed_state


def adopt_arrays(scope, arrays, shardings=None):
    """Install decoded host arrays into the scope, re-sharding each
    device value onto ``shardings`` (or its CURRENT sharding when the
    map has no entry — the unchanged-mesh case). Only called after
    the pod agreed every host's decode succeeded."""
    import jax
    for name, host_arr in sorted(arrays.items()):
        sh = None if shardings is None else shardings.get(name)
        if sh is None:
            cur = scope.find_var(name)
            if isinstance(cur, jax.Array):
                sh = cur.sharding
        scope.set_var(name, host_arr if sh is None
                      else jax.device_put(host_arr, sh))


def restore_agreed(co, hid, name, gen, scope, shardings=None,
                   need_feed_state=False, p2p=True):
    """Stage 2, after :func:`agree_plan` said ok: fetch + decode this
    host's snapshot (scope untouched), agree every host's decode
    outcome on gather #2, and only then adopt. Returns
    ``(True, feed_state)`` on success, ``(False, None)`` when any
    host's decode tore — nobody adopted anything, the caller takes
    the disk rewind with ``snapshot_torn``."""
    t0 = time.perf_counter()
    ok, arrays, feed_state = True, None, None
    try:
        with obs.span("buddy.restore", host=int(hid), gen=int(gen)):
            arrays, feed_state = fetch_and_decode(
                co, hid, gen, need_feed_state=need_feed_state, p2p=p2p)
    except Exception as e:
        ok = False
        record_event("buddy_decode_fail", host=int(hid), gen=int(gen),
                     error=type(e).__name__)
    outs = co.all_gather(name + "d", hid, bool(ok))
    if not all(outs.values()):
        return False, None
    adopt_arrays(scope, arrays, shardings=shardings)
    record_event("buddy_adopt", host=int(hid), gen=int(gen),
                 latency_s=round(time.perf_counter() - t0, 6))
    return True, feed_state
