"""Device places.

Reference parity: paddle/fluid/platform/place.h (CPUPlace/CUDAPlace/...).
TPU-first: TPUPlace is the primary device; it resolves to a jax TPU device.
"""
import jax


class Place(object):
    _backend = None

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    def jax_device(self):
        """Resolve to a concrete jax.Device."""
        if self._backend is None:  # "best available" place
            return jax.devices()[self.device_id]
        try:
            return jax.devices(self._backend)[self.device_id]
        except RuntimeError:
            # Backend unavailable (e.g. asking for TPU in a CPU-only test
            # environment): fall back to the default backend so programs stay
            # runnable everywhere.
            return jax.devices()[self.device_id]


class TPUPlace(Place):
    _backend = "tpu"


class CPUPlace(Place):
    _backend = "cpu"

    def __init__(self):
        super(CPUPlace, self).__init__(0)


class DefaultPlace(Place):
    """Whatever jax considers the default backend (TPU when attached)."""
    _backend = None


def _current_expected_place():
    # An active jax.default_device(...) pin (config or context manager) is
    # the caller's word on placement — honour it before consulting the
    # process-global backend list, so code running inside e.g. a CPU-pinned
    # dryrun never self-selects the attached TPU.
    pinned = getattr(jax.config, "jax_default_device", None)
    if pinned is not None:
        # jax accepts a Device object or a platform string here.
        platform = pinned if isinstance(pinned, str) \
            else getattr(pinned, "platform", None)
        if platform in ("tpu", "axon"):
            return TPUPlace(getattr(pinned, "id", 0))
        return CPUPlace()
    devs = jax.devices()
    if devs and devs[0].platform in ("tpu", "axon"):
        return TPUPlace(0)
    return CPUPlace()
