"""Program IR verifier: an analysis-pass framework over Program/Block/Op.

Reference parity: the reference lowers every Program through the
framework/ir/* graph-pass layer (Pass/PassRegistry + per-op InferShape)
before execution; paddle_tpu's pure-Python IR had no equivalent, so a
malformed program surfaced as an opaque jax traceback or a
first-named-error deep inside trace. This module closes that gap the
typed-IR-verification way (TVM, PAPERS.md): a pass manager walks the
Program — op registry + VarDesc metadata only, NO JAX tracing, no device
— and emits structured :class:`ProgramDiagnostic`s, reporting ALL
violations in one shot.

Shipped passes (PASS_NAMES order):
  def_use    — def-before-use / dangling reads + op_role section
               ordering (forward < backward < optimize)
  shape_dtype— static shape/dtype propagation through the registry's
               shape rules (ops/shape_rules.py; unknown ops infer top
               and never false-positive)
  sharding   — dp-divisibility of feed batch dims against the declared
               mesh, quantize_collectives' pure-dp requirement, mp-axis
               divisibility mirroring CompiledProgram._var_sharding
  pipeline   — pp stage stamps contiguous/monotone, stage homogeneity /
               chaining, auto-cut viability, update-section per-stage
               homogeneity — pre-checked BEFORE extract_compiled_pp_plan
  dce        — dead-op report against fetch-list + optimizer-update +
               collective liveness roots

Wiring: ``BuildStrategy.verify_program = "strict"|"warn"|"off"``
(default from PADDLE_TPU_VERIFY, else "warn") runs :func:`verify_program`
at CompilePlan build time (framework/compiler.py); ``tools/progcheck.py``
verifies serialized artifacts offline; ``ServingPredictor`` refuses a
corrupt exported program at load. Diagnostics feed the resilience
metrics as ``analysis_diagnostics_total{pass,severity}`` plus a
``program_analysis`` event (:func:`report`).
"""
import collections

from .program import Program
# the tracer's own sentinels — the verifier must model trace.py, so it
# shares them rather than re-declaring
from .trace import EMPTY_VAR, GRAD_OP_TYPE, STEP_VAR

SEVERITIES = ("info", "warning", "error")
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

PASS_DEF_USE = "def_use"
PASS_SHAPE = "shape_dtype"
PASS_SHARDING = "sharding"
PASS_PIPELINE = "pipeline"
PASS_DCE = "dce"
PASS_NAMES = (PASS_DEF_USE, PASS_SHAPE, PASS_SHARDING, PASS_PIPELINE,
              PASS_DCE)

# ops that are live roots regardless of dataflow (their effect is the
# collective / the persistable write, not a read of their outputs)
_SIDE_EFFECT_OPS = frozenset({"barrier", "ppermute", "c_sync_comm_stream"})


def _is_side_effect_op(op):
    return op.type in _SIDE_EFFECT_OPS or op.type.startswith("c_")


class ProgramDiagnostic(object):
    """One structured verifier finding.

    severity   -- "info" | "warning" | "error"
    pass_name  -- the analysis pass that produced it (PASS_NAMES)
    block_idx / op_idx / op_type -- program location (op_idx None for
                  program-level findings like a bad mesh)
    vars       -- tuple of involved var names
    message    -- what is wrong
    hint       -- how to fix it (may be "")
    """

    __slots__ = ("severity", "pass_name", "block_idx", "op_idx",
                 "op_type", "vars", "message", "hint")

    def __init__(self, severity, pass_name, message, block_idx=0,
                 op_idx=None, op_type=None, vars=(), hint=""):
        assert severity in SEVERITIES, severity
        self.severity = severity
        self.pass_name = pass_name
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.vars = tuple(vars)
        self.message = message
        self.hint = hint

    def location(self):
        loc = "block%d" % self.block_idx
        if self.op_idx is not None:
            loc += ":op%d" % self.op_idx
        if self.op_type:
            loc += "{%s}" % self.op_type
        return loc

    def to_dict(self):
        return {"severity": self.severity, "pass": self.pass_name,
                "block": self.block_idx, "op": self.op_idx,
                "op_type": self.op_type, "vars": list(self.vars),
                "message": self.message, "hint": self.hint}

    def __str__(self):
        s = "[%s] %s %s: %s" % (self.severity, self.pass_name,
                                self.location(), self.message)
        if self.vars:
            s += " (vars: %s)" % ", ".join(self.vars)
        if self.hint:
            s += " — " + self.hint
        return s

    __repr__ = __str__


class AnalysisResult(object):
    """All diagnostics of one verifier run, queryable by severity."""

    def __init__(self, diagnostics=()):
        self.diagnostics = list(diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self):
        return len(self.diagnostics)

    def by_severity(self, severity):
        return [d for d in self.diagnostics if d.severity == severity]

    def errors(self):
        return self.by_severity("error")

    def warnings(self):
        return self.by_severity("warning")

    def infos(self):
        return self.by_severity("info")

    def max_severity(self):
        """Highest severity present, or None for a clean program."""
        if not self.diagnostics:
            return None
        return max((d.severity for d in self.diagnostics),
                   key=_SEV_RANK.__getitem__)

    def exit_code(self):
        """progcheck contract: 0 clean/info, 1 warnings, 2 errors."""
        sev = self.max_severity()
        return {None: 0, "info": 0, "warning": 1, "error": 2}[sev]

    def counts(self):
        c = collections.Counter(d.severity for d in self.diagnostics)
        return {s: c.get(s, 0) for s in SEVERITIES}

    def summary(self):
        c = self.counts()
        head = "program verification: %d error(s), %d warning(s), " \
            "%d info" % (c["error"], c["warning"], c["info"])
        return "\n".join([head] + [str(d) for d in self.diagnostics])

    def to_dict(self):
        return {"counts": self.counts(),
                "diagnostics": [d.to_dict() for d in self.diagnostics]}


class ProgramVerificationError(ValueError):
    """Strict-mode failure: carries the FULL diagnostics list, so a bad
    program reads as located findings instead of one stack trace."""

    def __init__(self, result):
        self.result = result
        super(ProgramVerificationError, self).__init__(result.summary())


def allowlist(program, *pass_names, **kw):
    """Suppress the named passes' diagnostics for ``program`` — the
    explicit escape hatch for a vetted exception. Always pair the call
    with a comment explaining WHY the program is allowed to fail the
    pass. ``reason=`` is kept for introspection."""
    reason = kw.pop("reason", "")
    if kw:
        raise TypeError("unexpected kwargs %r" % sorted(kw))
    current = dict(getattr(program, "_analysis_allowlist", {}))
    for name in pass_names:
        if name not in PASS_NAMES:
            raise ValueError("unknown analysis pass %r (have %r)"
                             % (name, PASS_NAMES))
        current[name] = reason
    program._analysis_allowlist = current
    # drop memoized verdicts: an allowlist applied AFTER a program's
    # first compile must take effect on the next one, not only after
    # the program version happens to bump
    program._verify_cache = {}
    return program


# ---------------------------------------------------------------------------
# pass manager
# ---------------------------------------------------------------------------

_PASSES = []


def analysis_pass(name):
    """Register fn(ctx) -> iterable of ProgramDiagnostic under `name`."""
    def deco(fn):
        _PASSES.append((name, fn))
        return fn
    return deco


def registered_passes():
    return [name for name, _ in _PASSES]


class AnalysisContext(object):
    """Per-run state shared by the passes: the program plus everything
    the call site knows (feed shapes, fetch roots, mesh, strategy)."""

    def __init__(self, program, feeds=None, fetch_names=None,
                 mesh_axes=None, data_axis="dp", build_strategy=None):
        self.program = program
        self.fetch_names = tuple(fetch_names) \
            if fetch_names is not None else None
        self.mesh_axes = dict(mesh_axes) if mesh_axes else None
        self.data_axis = data_axis
        self.bs = build_strategy
        # feeds: {name: shape tuple or None}; None = feed set unknown
        if feeds is None:
            self.feeds = None
        elif isinstance(feeds, dict):
            self.feeds = {str(k): _np_shape(v) for k, v in feeds.items()}
        else:
            self.feeds = {str(k): None for k in feeds}
        blk = program.global_block()
        self.block0 = blk
        self.persistable = {v.name for b in program.blocks
                            for v in b.vars.values() if v.persistable}
        self.data_vars = {v.name for b in program.blocks
                          for v in b.vars.values()
                          if getattr(v, "is_data", False)}
        self.declared = {v.name for b in program.blocks
                         for v in b.vars.values()}
        # name -> index of the FIRST block-0 op producing it
        self.producer_idx = {}
        for i, op in enumerate(blk.ops):
            for n in op.output_names():
                if n != EMPTY_VAR:
                    self.producer_idx.setdefault(n, i)

    def bs_attr(self, name, default=None):
        return getattr(self.bs, name, default) if self.bs is not None \
            else default

    def pp_stages(self):
        k = self.bs_attr("pp_stages")
        if k:
            return int(k)
        if self.mesh_axes and int(self.mesh_axes.get("pp", 1) or 1) > 1:
            return int(self.mesh_axes["pp"])
        return None

    def feed_shape(self, name):
        """Best-known shape of a feed/var: the actual fed shape when the
        call site provided one, else the declared shape (-1 -> None)."""
        if self.feeds is not None and self.feeds.get(name) is not None:
            return self.feeds[name]
        var = self.block0._find_var_recursive(name)
        if var is not None and var.shape is not None:
            return tuple(None if d == -1 else d for d in var.shape)
        return None


def _np_shape(v):
    """Normalize a feed value or shape into a dim tuple (or None)."""
    if v is None:
        return None
    s = getattr(v, "shape", None)
    if s is None:
        s = v    # already a shape-like iterable
    try:
        return tuple(None if d is None or int(d) < 0 else int(d)
                     for d in s)
    except TypeError:
        return None


def verify_program(program, feeds=None, fetch_list=None, mesh_axes=None,
                   data_axis="dp", build_strategy=None, passes=None):
    """Run the analysis passes over ``program``; returns AnalysisResult.

    Pure and side-effect free: no counters, no events, no mutation of
    the program (pass :func:`report` the result to export metrics). The
    verifier never traces — a verify is a linear Python walk, safe to
    keep on by default.

    feeds       -- {name: shape} (the compile seam's actual feed
                   shapes), an iterable of feed names, or None (feed
                   set unknown — availability checks degrade to
                   warnings for declared vars)
    fetch_list  -- fetch names/Variables (the dce pass's liveness
                   roots); None disables the dead-op report
    mesh_axes / data_axis / build_strategy -- the strategy context for
                   the sharding and pipeline passes
    """
    if build_strategy is not None:
        if mesh_axes is None:
            mesh_axes = getattr(build_strategy, "mesh_axes", None)
        data_axis = getattr(build_strategy, "data_axis", data_axis)
    fetch_names = None
    if fetch_list is not None:
        fetch_names = [getattr(f, "name", f) for f in fetch_list]
    ctx = AnalysisContext(program, feeds=feeds, fetch_names=fetch_names,
                          mesh_axes=mesh_axes, data_axis=data_axis,
                          build_strategy=build_strategy)
    allow = getattr(program, "_analysis_allowlist", {})
    wanted = set(passes) if passes is not None else None
    out = []
    for name, fn in _PASSES:
        if wanted is not None and name not in wanted:
            continue
        if name in allow:
            continue
        try:
            out.extend(fn(ctx))
        except Exception as e:  # a pass bug must never block a compile
            out.append(ProgramDiagnostic(
                "warning", name,
                "analysis pass crashed: %s: %s" % (type(e).__name__, e),
                hint="report this — the pass is skipped, the program "
                     "still compiles"))
    return AnalysisResult(out)


def env_verify_mode():
    """The env-selected verifier mode: PADDLE_TPU_VERIFY = "strict" |
    "warn" | "off" (unset/unknown = "warn"). One parser for every
    consumer — BuildStrategy's default, the serving load gate."""
    import os
    raw = os.environ.get("PADDLE_TPU_VERIFY", "").strip().lower()
    return raw if raw in ("strict", "warn", "off") else "warn"


def verify_model_meta(meta, feeds=None, fetches=None):
    """Verify a serialized program envelope: an exported
    ``__model__.json`` meta (``{"program": ..., "feed_var_names": ...,
    "fetch_var_names": ...}``) or a bare ``Program.to_dict()`` dump.

    ONE implementation of the envelope contract for every gate —
    ``tools/progcheck.py`` (CI / offline) and ``ServingPredictor``
    (deploy drain) — so the two can never drift. Raises ValueError
    when the envelope itself is corrupt (as fatal as any error
    diagnostic: the artifact cannot be vetted); returns the
    AnalysisResult otherwise. ``feeds``/``fetches`` override the
    envelope's own lists."""
    if "program" in meta:
        prog_dict = meta["program"]
        if feeds is None:
            feeds = meta.get("feed_var_names")
        if fetches is None:
            fetches = meta.get("fetch_var_names")
    else:
        prog_dict = meta
    try:
        program = Program.from_dict(prog_dict)
    except (ValueError, KeyError, TypeError) as e:
        raise ValueError("corrupt program IR (%s: %s)"
                         % (type(e).__name__, e))
    return verify_program(program, feeds=feeds, fetch_list=fetches)


def report(result, mode="warn", source="compile"):
    """Export one verification's outcome: bump the
    ``analysis_diagnostics_total{pass,severity}`` counters and record a
    ``program_analysis`` event on the resilience surface."""
    from . import resilience
    for d in result:
        resilience.record_analysis(d.pass_name, d.severity)
    c = result.counts()
    resilience.record_event("program_analysis", source=source, mode=mode,
                            errors=c["error"], warnings=c["warning"],
                            infos=c["info"])


# ---------------------------------------------------------------------------
# pass 1: def-use / liveness forward walk + section ordering
# ---------------------------------------------------------------------------

@analysis_pass(PASS_DEF_USE)
def _pass_def_use(ctx):
    out = []
    blk = ctx.block0
    # section ordering: forward < backward < optimize. Info severity:
    # backward-after-optimize is how SUPPORTED patterns look too —
    # pt.gradients() after minimize(), DCGAN's two-optimizer
    # adversarial step — but the report still flags where the sections
    # interleave, because gradients taken there flow through
    # ALREADY-UPDATED params (exactly what an adversarial step wants
    # and an accidental re-minimize does not).
    first_opt = next((i for i, op in enumerate(blk.ops)
                      if op.attrs.get("op_role") == "optimize"), None)
    if first_opt is not None:
        for i in range(first_opt + 1, len(blk.ops)):
            op = blk.ops[i]
            if op.attrs.get("op_role") == "backward":
                out.append(ProgramDiagnostic(
                    "info", PASS_DEF_USE,
                    "backward-role op appears after the optimize section "
                    "began (op %d) — sections interleave (forward < "
                    "backward < optimize); its gradients flow through "
                    "already-updated params" % first_opt,
                    op_idx=i, op_type=op.type,
                    hint="intentional for adversarial/two-optimizer "
                         "steps and gradients()-after-minimize; "
                         "otherwise rebuild via minimize()"))
    if ctx.program.num_blocks > 1:
        # control-flow sub-blocks resolve reads through the parent env
        # at trace time — the straight-line walk below would
        # false-positive, so multi-block programs skip it (conservative)
        return out
    available = set(ctx.persistable) | {EMPTY_VAR, STEP_VAR}
    if ctx.feeds is not None:
        available |= set(ctx.feeds)
    else:
        available |= ctx.data_vars
    produced = set()
    for i, op in enumerate(blk.ops):
        for n in op.input_names():
            if n in available or n in produced or n == EMPTY_VAR:
                continue
            later = n in ctx.producer_idx and ctx.producer_idx[n] >= i
            feedable = ctx.feeds is None and n in ctx.declared
            if later:
                if feedable:
                    sev, what = "warning", \
                        "read before its producer (op %d) and not known " \
                        "to be fed" % ctx.producer_idx[n]
                else:
                    sev, what = "error", \
                        "read before its producer (op %d)" \
                        % ctx.producer_idx[n]
                hint = "move the producer above, or feed the var"
            elif n in ctx.declared:
                sev = "error" if ctx.feeds is not None else "warning"
                what = "is never produced, fed, or persistable — the " \
                    "trace would fail with a missing-value error"
                hint = "feed it, mark it persistable+initialized, or " \
                    "add the producing op"
            else:
                sev = "error"
                what = "is not declared in any block and never produced " \
                    "— a dangling read"
                hint = "the op references a var that does not exist; " \
                    "check the program transform that renamed it"
            out.append(ProgramDiagnostic(
                sev, PASS_DEF_USE,
                "op input %r %s" % (n, what),
                op_idx=i, op_type=op.type, vars=(n,), hint=hint))
        produced.update(x for x in op.output_names() if x != EMPTY_VAR)
    return out


# ---------------------------------------------------------------------------
# pass 2: static shape/dtype inference through the registry rules
# ---------------------------------------------------------------------------

def _declared_meta(ctx, name):
    from ..ops.shape_rules import TensorMeta
    var = ctx.block0._find_var_recursive(name)
    if var is None:
        return TensorMeta(None, None)
    shape = None
    if var.shape is not None:
        shape = tuple(None if d == -1 else d for d in var.shape)
    return TensorMeta(shape, var.dtype)


@analysis_pass(PASS_SHAPE)
def _pass_shape_dtype(ctx):
    from ..ops.registry import get_shape_rule
    from ..ops.shape_rules import ShapeError, TensorMeta
    out = []
    env = {}

    def meta_of(name):
        if name == EMPTY_VAR:
            return TensorMeta(None, None)
        m = env.get(name)
        if m is None:
            m = _declared_meta(ctx, name)
            if ctx.feeds is not None and \
                    ctx.feeds.get(name) is not None:
                m = TensorMeta(ctx.feeds[name], m.dtype)
            env[name] = m
        return m

    def bind(op, results):
        for slot, names in op.outputs.items():
            vals = (results or {}).get(slot) or []
            for j, n in enumerate(names):
                if n == EMPTY_VAR:
                    continue
                env[n] = vals[j] if j < len(vals) else TensorMeta()

    for i, op in enumerate(ctx.block0.ops):
        if op.type == GRAD_OP_TYPE:
            # a gradient has its forward input's metadata, by definition
            for slot, names in op.outputs.items():
                if not slot.startswith("IG:"):
                    continue
                fwd = op.inputs.get("X:" + slot[len("IG:"):], [])
                for j, n in enumerate(names):
                    if n == EMPTY_VAR:
                        continue
                    env[n] = meta_of(fwd[j]) if j < len(fwd) \
                        else TensorMeta()
            continue
        rule = get_shape_rule(op.type)
        if rule is None:
            bind(op, None)
            continue
        ins = {slot: [meta_of(n) for n in names]
               for slot, names in op.inputs.items()}
        try:
            results = rule(op, ins, op.attrs)
        except ShapeError as e:
            out.append(ProgramDiagnostic(
                e.severity, PASS_SHAPE, str(e), op_idx=i,
                op_type=op.type, vars=tuple(op.input_names()[:4]),
                hint="fix the operand shapes/dtypes at this op's "
                     "program location (build-time), not inside jit"))
            results = None
        except Exception as e:  # a broken rule must not block compiles
            out.append(ProgramDiagnostic(
                "warning", PASS_SHAPE,
                "shape rule for {%s} crashed: %s: %s"
                % (op.type, type(e).__name__, e), op_idx=i,
                op_type=op.type,
                hint="report this — the op infers unknown"))
            results = None
        bind(op, results)
    return out


# ---------------------------------------------------------------------------
# pass 3: sharding feasibility against the declared mesh
# ---------------------------------------------------------------------------

@analysis_pass(PASS_SHARDING)
def _pass_sharding(ctx):
    out = []
    mesh = ctx.mesh_axes
    if not mesh:
        return out
    if ctx.bs_attr("quantize_collectives", False):
        allow = {ctx.data_axis, "pp"}
        bad = {a: int(s) for a, s in mesh.items()
               if a not in allow and int(s) > 1}
        if bad:
            out.append(ProgramDiagnostic(
                "error", PASS_SHARDING,
                "quantize_collectives supports pure data-parallel "
                "meshes only; model axes %r would lose their "
                "XLA-inserted collectives" % (bad,),
                hint="drop quantize_collectives or the model axes"))
    dp = int(mesh.get(ctx.data_axis, 1) or 1)
    if dp > 1 and ctx.feeds is not None:
        for name in sorted(ctx.feeds):
            shape = ctx.feed_shape(name)
            if not shape or shape[0] is None:
                continue
            if shape[0] % dp != 0:
                out.append(ProgramDiagnostic(
                    "warning", PASS_SHARDING,
                    "feed %r batch dim %d does not divide the %r mesh "
                    "axis (%d) — the feed stays replicated and every "
                    "shard computes the full batch"
                    % (name, shape[0], ctx.data_axis, dp),
                    vars=(name,),
                    hint="pad the batch to a multiple of %d or resize "
                         "the mesh" % dp))
    for blk in ctx.program.blocks:
        for var in blk.vars.values():
            if not getattr(var, "sharding", None):
                continue
            shape = var.shape or ()
            for dim_i, axis in enumerate(var.sharding):
                if axis is None:
                    continue
                if axis not in mesh:
                    out.append(ProgramDiagnostic(
                        "info", PASS_SHARDING,
                        "var %r is annotated to shard dim %d over mesh "
                        "axis %r which the mesh %r does not have — the "
                        "dim stays replicated" % (var.name, dim_i, axis,
                                                  sorted(mesh)),
                        block_idx=blk.idx, vars=(var.name,)))
                    continue
                size = int(mesh[axis])
                if dim_i < len(shape) and shape[dim_i] not in (None, -1) \
                        and size > 1 and shape[dim_i] % size != 0:
                    out.append(ProgramDiagnostic(
                        "warning", PASS_SHARDING,
                        "var %r dim %d (%d) does not divide mesh axis "
                        "%r (%d) — the dim stays replicated instead of "
                        "sharding" % (var.name, dim_i, shape[dim_i],
                                      axis, size),
                        block_idx=blk.idx, vars=(var.name,),
                        hint="size the dim to a multiple of %d" % size))
    return out


# ---------------------------------------------------------------------------
# pass 4: pipeline feasibility (pre-checks extract_compiled_pp_plan)
# ---------------------------------------------------------------------------

@analysis_pass(PASS_PIPELINE)
def _pass_pipeline(ctx):
    out = []
    k = ctx.pp_stages()
    if not k or k < 2:
        return out
    from ..distributed import pipeline_program as ppp
    blk = ctx.block0
    err = lambda msg, **kw: out.append(  # noqa: E731
        ProgramDiagnostic("error", PASS_PIPELINE, msg, **kw))

    schedule = ctx.bs_attr("pp_schedule", "1f1b")
    if schedule not in ("1f1b", "gpipe"):
        err("pp_schedule %r is not one of ('1f1b', 'gpipe')" % schedule,
            hint="pick a supported pipeline schedule")
    mesh_pp = int((ctx.mesh_axes or {}).get("pp", 0) or 0)
    bs_k = ctx.bs_attr("pp_stages")
    recut_n = int(ctx.bs_attr("pp_recut_slots") or 0)
    if recut_n:
        # elastic re-cut armed: the mesh pp axis counts SLOTS, each
        # holding >= 1 logical stages; feasibility is the ceil(K/2) bound
        if mesh_pp and recut_n != mesh_pp:
            err("pp_recut_slots=%d does not match the mesh's pp axis "
                "(%d)" % (recut_n, mesh_pp),
                hint="the re-cut mesh carries one slot per surviving "
                     "pp rank")
        if bs_k and recut_n > int(bs_k):
            err("pp_recut_slots=%d exceeds pp_stages=%d — a re-cut "
                "slot cannot be empty" % (recut_n, int(bs_k)),
                hint="clear pp_recut_slots to grow back to the "
                     "1-stage-per-slot plan")
    elif bs_k and mesh_pp and int(bs_k) != mesh_pp:
        err("pp_stages=%d does not match the mesh's pp axis (%d)"
            % (int(bs_k), mesh_pp),
            hint="make BuildStrategy.pp_stages agree with mesh_axes")
    n_micro = int(ctx.bs_attr("pp_micro_batches", 1) or 1)
    if ctx.feeds:
        for name in sorted(ctx.feeds):
            shape = ctx.feed_shape(name)
            if shape and shape[0] is not None and n_micro > 1 \
                    and shape[0] % n_micro != 0:
                err("feed %r batch %d is not divisible by "
                    "pp_micro_batches=%d" % (name, shape[0], n_micro),
                    vars=(name,),
                    hint="pick a batch size that is a multiple of the "
                         "microbatch count")

    ops = blk.ops
    first_bwd = next((i for i, op in enumerate(ops)
                      if op.attrs.get("op_role") == "backward"), None)
    if first_bwd is None:
        err("the pipeline path lowers the whole fwd+bwd+optimizer step "
            "— minimize() the loss first (the program has no backward "
            "section)",
            hint="call optimizer.minimize(loss) before compiling with "
                 "pp_stages")
        return out
    seed_op = ops[first_bwd]
    if seed_op.type != "fill_any_like" or "X" not in seed_op.inputs:
        err("cannot identify the loss: the backward section does not "
            "start with the append_backward seed",
            op_idx=first_bwd, op_type=seed_op.type,
            hint="multi-target gradients() programs are not supported "
                 "on the pp path")
        return out
    fwd_ops = ops[:first_bwd]

    stamped_idx = [(i, int(op.attrs["pp_stage"]))
                   for i, op in enumerate(fwd_ops)
                   if "pp_stage" in op.attrs]
    if not stamped_idx:
        # auto-cut viability, side-effect free: probe the stamping on a
        # throwaway CLONE so the real program is never mutated here
        if len(fwd_ops) < k:
            err("auto-cut cannot split %d forward ops into %d pipeline "
                "stages" % (len(fwd_ops), k),
                hint="lower pp_stages or stamp the model explicitly "
                     "with pp_stage_guard(stage)")
            return out
        clone = ctx.program.clone()
        loss_name = seed_op.inputs["X"][0]
        try:
            ppp._auto_stamp(clone, clone.global_block().ops[:first_bwd],
                            k, loss_name, schedule, max(1, n_micro))
        except ValueError as e:
            err("auto-cut is not viable: %s" % e,
                hint="stamp the model explicitly with "
                     "pp_stage_guard(stage)")
        return out

    stages = sorted({s for _, s in stamped_idx})
    if stages != list(range(len(stages))):
        err("pp_stage stamps must be contiguous 0..n-1; got %r" % stages,
            hint="renumber the pp_stage_guard sections")
        return out
    if bs_k and len(stages) != int(bs_k):
        err("BuildStrategy.pp_stages=%d but the program is stamped with "
            "%d pipeline stages — they do not match"
            % (int(bs_k), len(stages)),
            hint="make the guard sections and the strategy agree")
    head = [i for i, op in enumerate(fwd_ops)
            if "pp_stage" not in op.attrs and i < stamped_idx[0][0]]
    for i in head:
        err("op before the first pipeline stage is not supported (v1)",
            op_idx=i, op_type=fwd_ops[i].type,
            hint="move the op inside pp_stage_guard(0) or after the "
                 "stages")
    last = -1
    for i, s in stamped_idx:
        if s < last:
            err("pp_stage stamps are not monotone: stage %d appears "
                "after stage %d" % (s, last), op_idx=i,
                op_type=fwd_ops[i].type,
                hint="emit each stage's ops contiguously")
            return out
        last = s
    n_stage = len(stages)
    groups = {s: [op for op in fwd_ops
                  if op.attrs.get("pp_stage") == s]
              for s in range(n_stage)}
    sig0 = ppp._stage_signature(groups[0])
    for s in range(1, n_stage):
        if ppp._stage_signature(groups[s]) != sig0:
            err("pipeline stages must be structurally identical (SPMD "
                "GPipe/1F1B contract); stage %d differs from stage 0"
                % s, hint="make every pp_stage_guard section emit the "
                          "same op sequence")
    per_stage_io = []
    for s in range(n_stage):
        try:
            per_stage_io.append(ppp._stage_io(blk, groups[s]))
        except ValueError as e:
            err("stage %d: %s" % (s, e))
            per_stage_io.append(None)
    for s in range(1, n_stage):
        a, b = per_stage_io[s - 1], per_stage_io[s]
        if a is None or b is None:
            continue
        if b[1] != a[2]:
            err("stage %d consumes %r but stage %d produces %r — "
                "stages must chain" % (s, b[1], s - 1, a[2]),
                vars=(b[1], a[2]),
                hint="wire each stage's output into the next stage")
    if any(io is None for io in per_stage_io) or \
            any(ppp._stage_signature(groups[s]) != sig0
                for s in range(1, n_stage)):
        return out

    # update-section homogeneity (the post-backward non-grad ops): the
    # SPMD cut runs ONE stage-0 template on every pp shard's state
    # slice, so the sections must be positionally parallel
    from .trace import GRAD_SUFFIX
    update_all = [(i, op) for i, op in enumerate(ops[first_bwd:],
                                                 start=first_bwd)
                  if op.attrs.get("op_role") != "backward"]
    stage_of = {}
    for s in range(n_stage):
        for pname in per_stage_io[s][0]:
            stage_of[pname] = s
            stage_of[pname + GRAD_SUFFIX] = s
    tagged = []
    for i, op in update_all:
        in_stages = {stage_of[nm] for nm in op.input_names()
                     if nm in stage_of}
        if len(in_stages) > 1:
            err("update op reads state of multiple pipeline stages "
                "(%r) — cross-stage update ops (e.g. a global "
                "grad-norm clip) are not supported on the pp path"
                % sorted(in_stages), op_idx=i, op_type=op.type,
                hint="clip/update per stage instead")
            return out
        s = in_stages.pop() if in_stages else None
        tagged.append((op, s))
        if s is not None:
            for nm in op.output_names():
                stage_of[nm] = s
    ugroups = {s: [op for op, st in tagged if st == s]
               for s in range(n_stage)}
    usig0 = ppp._stage_signature(ugroups[0])
    for s in range(1, n_stage):
        if ppp._stage_signature(ugroups[s]) != usig0:
            err("the update section for pipeline stage %d is not "
                "structurally identical to stage 0's — the SPMD pp "
                "path runs ONE update template on every stage's slice"
                % s, hint="use the same optimizer/LR wiring for every "
                          "stage's params")
    return out


# ---------------------------------------------------------------------------
# pass 5: dead-op / DCE report
# ---------------------------------------------------------------------------

@analysis_pass(PASS_DCE)
def _pass_dce(ctx):
    out = []
    if ctx.fetch_names is None or ctx.program.num_blocks > 1:
        # without fetch roots any leaf could be the fetch; with
        # sub-blocks reads cross block boundaries — both would
        # false-positive, so the report needs the compile seam's roots
        return out
    live = set(ctx.fetch_names)
    dead = []
    for i in range(len(ctx.block0.ops) - 1, -1, -1):
        op = ctx.block0.ops[i]
        outs = [n for n in op.output_names() if n != EMPTY_VAR]
        is_live = (_is_side_effect_op(op)
                   or any(n in live for n in outs)
                   or any(n in ctx.persistable for n in outs))
        if is_live:
            live.update(n for n in op.input_names() if n != EMPTY_VAR)
        else:
            dead.append((i, op, outs))
    for i, op, outs in reversed(dead):
        out.append(ProgramDiagnostic(
            "info", PASS_DCE,
            "dead op: no output reaches the fetch list, a persistable "
            "update, or a collective — XLA will DCE it, but it still "
            "costs trace time", op_idx=i, op_type=op.type,
            vars=tuple(outs[:4]),
            hint="drop the op or fetch its output"))
    return out
