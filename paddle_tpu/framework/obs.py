"""obs — the distributed-tracing spans engine.

Reference parity: the reference stack ships a real profiler
(python/paddle/fluid/profiler.py + tools/timeline.py renders
chrome://tracing timelines of op runs). ``paddle_tpu/profiler.py``
wraps jax.profiler — which sees XLA internals but nothing of OUR
layers — and the system now spans processes (routers, replicas,
replicated CoordServers, elastic pods) where the questions that matter
("where did this request's 800ms go — queue, coalesce, dispatch,
replica step, or retry?") cross process boundaries no per-process
metric can attribute. This module is the layer that can: cheap
in-process spans with DISTRIBUTED trace context.

Design:

  * A **span** is one timed operation: ``(trace, id, parent, name,
    t0, t1, labels, tid)``. Trace/span ids are random hex; parentage
    links spans into one request tree ACROSS processes.
  * **Trace context** rides a thread-local stack in-process and the
    ``x-trace-id: <trace>:<span>`` HTTP header between processes
    (:func:`header` / :func:`parse_header`).
  * Finished spans land in a **bounded per-process ring**
    (``PADDLE_TPU_TRACE_RING``, default 8192); overflow evicts the
    oldest and counts ``dropped_total()`` — exported by
    ``resilience.metrics()`` as ``trace_spans_dropped_total`` so a
    lying (truncated) timeline is loud, never silent.
  * **Near-zero cost when disabled** (the default): :func:`span`
    checks one module flag and returns a shared no-op context
    manager — no allocation, no clock read. Enable with
    ``PADDLE_TPU_TRACE=1`` or :func:`enable`.
  * **Timestamps** are wall-clock anchored monotonic seconds: each
    process pins ``(time.time(), time.monotonic())`` once at import
    and every span time is ``anchor_wall + (mono - anchor_mono)`` —
    monotonic within the process, comparable across same-host
    processes. For multi-host alignment :func:`probe_clock_offset`
    measures this process's offset against the coordination server's
    clock (min-RTT sample of the ``time`` op) and the offset is
    applied at EXPORT time, so all processes land on the
    coordinator's timeline.
  * **Export** is the Chrome trace event format
    (:func:`chrome_trace`): one Perfetto-loadable JSON merging any
    number of per-process :func:`dump_dict` blobs —
    ``tools/traceview.py`` is the CLI (files and/or live
    ``/admin/trace`` pulls).

Span taxonomy (what the built-in instrumentation emits) is documented
in PORTING.md "Observability & tracing".
"""
import contextlib
import collections
import json
import os
import random
import threading
import time

__all__ = [
    "enabled", "enable", "disable", "span", "record", "current",
    "new_trace_id", "header", "parse_header", "spans", "clear",
    "dropped_total", "set_service", "service", "dump_dict", "dump",
    "clock_offset", "set_clock_offset", "probe_clock_offset",
    "chrome_trace", "now", "RING_CAPACITY",
]

RING_CAPACITY = int(os.environ.get("PADDLE_TPU_TRACE_RING", "8192")
                    or 8192)

# one wall anchor per process: span times are monotonic WITHIN the
# process but live on the wall-clock axis, so same-host processes
# already align and the coordinator offset handles the rest
_ANCHOR_WALL = time.time()
_ANCHOR_MONO = time.monotonic()

_state = {
    "enabled": os.environ.get("PADDLE_TPU_TRACE", "") not in ("", "0"),
    "service": os.environ.get("PADDLE_TPU_TRACE_SERVICE") or None,
    "service_env": bool(os.environ.get("PADDLE_TPU_TRACE_SERVICE")),
    "clock_offset": 0.0,
    "dropped": 0,
}
_ring = collections.deque(maxlen=RING_CAPACITY)
_lock = threading.Lock()
_tls = threading.local()
# ids from the process-seeded global RNG would correlate across forked
# workers; a dedicated SystemRandom never collides
_rng = random.SystemRandom()


def now():
    """The engine's timebase: wall-anchored monotonic seconds. Use for
    retroactive :func:`record` timestamps so they live on the same
    axis as context-manager spans."""
    return _ANCHOR_WALL + (time.monotonic() - _ANCHOR_MONO)


def enabled():
    return _state["enabled"]


def enable(service=None):
    """Turn the spans engine on (idempotent). ``service`` names this
    process in merged timelines (falls back to ``pid<pid>``)."""
    if service is not None:
        set_service(service)
    _state["enabled"] = True


def disable():
    _state["enabled"] = False


def set_service(name, force=True):
    """Name this process for merged timelines. ``force=False`` keeps
    an operator-provided PADDLE_TPU_TRACE_SERVICE (or an earlier
    explicit set) — how ReplicaMember/FleetRouter self-name without
    clobbering deployment config."""
    if not force and (_state["service_env"]
                      or _state["service"] is not None):
        return
    _state["service"] = str(name)


def service():
    return _state["service"] or ("pid%d" % os.getpid())


def new_trace_id():
    return "%016x" % _rng.getrandbits(64)


def _new_span_id():
    return "%08x" % _rng.getrandbits(32)


def current():
    """(trace_id, span_id) of this thread's innermost open span, or
    ``None`` — what child spans and outgoing headers parent under."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return None
    return stack[-1]


def header(ctx=None):
    """The ``x-trace-id`` header value for the current (or given)
    context: ``"<trace>:<span>"``; None when there is nothing open."""
    ctx = ctx if ctx is not None else current()
    if not ctx:
        return None
    return "%s:%s" % ctx


def parse_header(value):
    """Parse an ``x-trace-id`` header into ``(trace_id,
    parent_span_id)``; ``(None, None)`` for absent/malformed values —
    a bad header degrades to an un-traced request, never a 500."""
    if not value or not isinstance(value, str):
        return None, None
    parts = value.strip().split(":")
    if len(parts) != 2 or not parts[0]:
        return None, None
    return parts[0], (parts[1] or None)


def _push(trace, span_id):
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append((trace, span_id))


def _pop():
    stack = getattr(_tls, "stack", None)
    if stack:
        stack.pop()


def _commit(entry):
    with _lock:
        if len(_ring) == _ring.maxlen:
            _state["dropped"] += 1
        _ring.append(entry)


class _Span(object):
    """An OPEN span (context manager). ``set(**labels)`` annotates it
    mid-flight (outcome labels land just before close)."""

    __slots__ = ("trace", "id", "parent", "name", "t0", "labels")

    def __init__(self, name, trace, parent, labels):
        self.name = name
        self.trace = trace
        self.id = _new_span_id()
        self.parent = parent
        self.labels = labels
        self.t0 = now()

    def set(self, **labels):
        self.labels.update(labels)
        return self

    def __enter__(self):
        _push(self.trace, self.id)
        return self

    def __exit__(self, exc_type, exc, tb):
        _pop()
        if exc_type is not None and "error" not in self.labels:
            self.labels["error"] = exc_type.__name__
        _commit({"trace": self.trace, "id": self.id,
                 "parent": self.parent, "name": self.name,
                 "t0": self.t0, "t1": now(), "labels": self.labels,
                 "tid": threading.current_thread().name})
        return False


class _Noop(object):
    """The disabled path: one shared instance, no allocation."""

    __slots__ = ()
    trace = id = parent = None

    def set(self, **labels):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _Noop()


def span(name, trace_id=None, parent=None, **labels):
    """Open a span as a context manager.

    With no explicit ``trace_id`` the span joins the thread's current
    trace (starting a fresh one at the root); ``parent`` defaults to
    the innermost open span. Explicit ``trace_id``/``parent`` attach
    to REMOTE context (:func:`parse_header`). A no-op (shared
    singleton, no clock read) while the engine is disabled."""
    if not _state["enabled"]:
        return _NOOP
    if trace_id is None:
        cur = current()
        if cur is not None:
            trace_id = cur[0]
            if parent is None:
                parent = cur[1]
        else:
            trace_id = new_trace_id()
    return _Span(name, trace_id, parent, labels)


def record(name, t0, t1, trace_id=None, parent=None, **labels):
    """Record an ALREADY-FINISHED span retroactively (timestamps from
    :func:`now`) — how the router accounts a request's queue wait
    after the batch cut, without holding an open span per queued
    request. Joins the thread's current trace when no explicit
    ``trace_id`` is given (same defaulting as :func:`span`). Returns
    the span id (None while disabled)."""
    if not _state["enabled"]:
        return None
    if trace_id is None:
        cur = current()
        if cur is not None:
            trace_id = cur[0]
            if parent is None:
                parent = cur[1]
    sid = _new_span_id()
    _commit({"trace": trace_id or new_trace_id(), "id": sid,
             "parent": parent, "name": name, "t0": float(t0),
             "t1": float(t1), "labels": labels,
             "tid": threading.current_thread().name})
    return sid


def spans(trace_id=None, name=None):
    """Snapshot of the ring (optionally filtered)."""
    with _lock:
        out = list(_ring)
    if trace_id is not None:
        out = [s for s in out if s["trace"] == trace_id]
    if name is not None:
        out = [s for s in out if s["name"] == name]
    return out


def dropped_total():
    with _lock:
        return _state["dropped"]


def clear():
    with _lock:
        _ring.clear()
        _state["dropped"] = 0


# ---------------------------------------------------------------------------
# cross-process clock alignment
# ---------------------------------------------------------------------------

def clock_offset():
    return _state["clock_offset"]


def set_clock_offset(seconds):
    _state["clock_offset"] = float(seconds)


def probe_clock_offset(call, samples=5):
    """Estimate this process's clock offset against the coordination
    server and install it (applied to every exported timestamp).

    ``call(cmd)`` is a request function returning the server's
    response dict — e.g. ``lambda cmd: coord._call(cmd)`` against the
    CoordServer ``time`` op (``{"wall": <server time.time()>}``). The
    classic NTP-style midpoint estimate, keeping the MINIMUM-RTT
    sample (least queueing noise): ``offset = server_wall -
    (t0+t1)/2``. Same-host fleets land near zero; multi-host fleets
    land every process on the coordinator's timeline."""
    best = None
    for _ in range(max(1, int(samples))):
        t0 = now()
        resp = call("time")
        t1 = now()
        off = float(resp["wall"]) - (t0 + t1) / 2.0
        rtt = t1 - t0
        if best is None or rtt < best[0]:
            best = (rtt, off)
    set_clock_offset(best[1])
    return best[1]


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def dump_dict():
    """This process's span dump: what ``/admin/trace`` serves and
    ``tools/traceview.py`` merges. Timestamps stay RAW; the recorded
    ``clock_offset_s`` is applied by the merge so re-probing never
    double-shifts."""
    return {"format": "paddle_tpu_trace", "version": 1,
            "service": service(), "pid": os.getpid(),
            "clock_offset_s": clock_offset(),
            "dropped": dropped_total(), "spans": spans()}


def dump(path):
    """Write :func:`dump_dict` to ``path`` (one JSON object)."""
    with open(path, "w") as f:
        json.dump(dump_dict(), f)
    return path


def chrome_trace(dumps=None):
    """Merge per-process span dumps into ONE Chrome-trace-event JSON
    (``{"traceEvents": [...]}``, Perfetto / chrome://tracing
    loadable). ``dumps`` is a list of :func:`dump_dict`-shaped blobs
    (default: this process's own). Every span becomes a complete
    ("X") event carrying its trace/span/parent ids in ``args`` so the
    cross-process parentage survives into the viewer; process and
    thread metadata events name the lanes."""
    if dumps is None:
        dumps = [dump_dict()]
    events = []
    for d in dumps:
        pid = int(d.get("pid") or 0)
        off = float(d.get("clock_offset_s") or 0.0)
        svc = d.get("service") or ("pid%d" % pid)
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": svc}})
        tids = {}
        for s in d.get("spans", ()):
            tname = s.get("tid") or "main"
            tid = tids.get(tname)
            if tid is None:
                tid = tids[tname] = len(tids) + 1
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": tname}})
            args = dict(s.get("labels") or {})
            args.update({"trace_id": s["trace"], "span_id": s["id"],
                         "parent_id": s.get("parent"),
                         "service": svc})
            events.append({
                "ph": "X", "cat": "paddle_tpu", "name": s["name"],
                "pid": pid, "tid": tid,
                "ts": round((s["t0"] + off) * 1e6, 3),
                "dur": round(max(0.0, s["t1"] - s["t0"]) * 1e6, 3),
                "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
