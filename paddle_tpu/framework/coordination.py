"""Pod recovery control plane — agreed restores for multi-host training.

Reference parity: the reference stack recovers pserver fleets as a UNIT
(`operators/distributed` + fleet roles: trainers reconnect, pservers
re-serve tables, the whole job restarts from one snapshot). On TPU there
is no pserver tier — the ICI collectives that replace the RPC layer
(psum/all_gather inside the jitted step) deadlock if any host resumes at
a different step than its peers, so recovery must be AGREED: either
every host rewinds to one quorum-validated checkpoint step, or none
does. framework/resilience.py closes the detect->recover loop for ONE
process; this module is the pod half:

  * :class:`Coordinator` — the contract: ``barrier`` / ``all_gather`` /
    ``elect_restore_step`` (consensus = max step for which a
    scrub-validated checkpoint exists on every live host), plus
    host-loss detection that fires mesh re-initialization hooks
    (distributed/mesh.py) so survivors rebuild collectives without the
    dead host.
  * :class:`LocalCoordinator` — in-process, thread-based. Drives tier-1
    tests and single-process simulations of an N-host pod (the ``pod``
    pytest marker).
  * :class:`FileCoordinator` — file-based, for real multi-process pods
    sharing a filesystem. Every contribution is an atomic file write;
    no shared memory, so N processes each owning one FileCoordinator
    object agree through the directory alone.
  * :class:`PodResilientTrainer` — wraps N per-host
    :class:`~.resilience.ResilientTrainer` s. Every dispatch window ends
    in a status exchange; if ANY host saw a transient fault, every host
    scrubs its checkpoint dir (``io.scrub_checkpoint`` — manifest +
    shard headers, never array payloads), the coordinator elects the
    consensus step, and ALL hosts restore it and replay. The replayed
    trajectory is bitwise-identical to a fault-free run, and the
    restart budget is shared: rewinds are pod-wide, so every host's
    budget counter advances in lockstep.
"""
import collections
import threading
import time

from .resilience import RestartBudgetExceededError, record_event

__all__ = [
    "CoordinationError", "HostLostError", "BarrierTimeoutError",
    "NoQuorumError", "Coordinator", "LocalCoordinator",
    "FileCoordinator", "PodResilientTrainer",
]


class CoordinationError(RuntimeError):
    """A pod-level coordination failure (peer fatal, protocol misuse)."""


class HostLostError(CoordinationError):
    """This host was marked lost (fenced): it missed a barrier or was
    declared dead. A fenced host must NOT keep training — rejoin via the
    orchestrator as a fresh participant instead of split-braining."""


class BarrierTimeoutError(CoordinationError):
    """A collective did not complete in time and loss detection was
    disabled, so nobody was marked lost — the caller decides."""


class NoQuorumError(CoordinationError):
    """No checkpoint step is valid on enough live hosts to restore —
    escalate to the orchestrator (cold start or manual repair)."""


# ---------------------------------------------------------------------------
# coordinator contract + shared consensus logic
# ---------------------------------------------------------------------------

class Coordinator(object):
    """Base contract. Subclasses implement :meth:`all_gather` plus the
    live/lost bookkeeping; everything else (barrier, consensus election,
    host-loss hook fan-out) is shared.

    Host-loss semantics: when a collective times out, the hosts that
    never arrived are marked LOST (``detect_loss=True``), the remaining
    values are returned to the survivors, and the loss hooks fire —
    including mesh re-initialization (``distributed.mesh
    .handle_host_loss``) so the survivors' collectives are rebuilt
    without the dead host. A lost host that later calls in gets
    :class:`HostLostError` (fencing: it must rejoin, not resume).
    """

    def __init__(self, n_hosts, timeout_s=30.0, detect_loss=True,
                 mesh_reinit=True):
        if n_hosts < 1:
            raise ValueError("n_hosts must be >= 1")
        self.n_hosts = int(n_hosts)
        self.timeout_s = float(timeout_s)
        self.detect_loss = bool(detect_loss)
        self._mesh_reinit = bool(mesh_reinit)
        self._loss_hooks = []

    # -- subclass surface --------------------------------------------------
    def all_gather(self, name, host_id, value=None, timeout_s=None):
        """Collective: every live host contributes ``value`` under the
        (round-unique) ``name``; returns {host_id: value} of the live
        participants. Blocks until all live hosts arrive or the timeout
        handles the missing ones (see class docstring)."""
        raise NotImplementedError

    def live_hosts(self):
        raise NotImplementedError

    def lost_hosts(self):
        """{host_id: reason} of every host marked lost so far."""
        raise NotImplementedError

    def mark_lost(self, host_id, reason="declared lost"):
        raise NotImplementedError

    # -- shared machinery --------------------------------------------------
    def add_host_loss_hook(self, fn):
        """Register ``fn(lost_ids, live_ids)`` to run on host loss (after
        the built-in mesh re-init). Returns fn for decorator use."""
        self._loss_hooks.append(fn)
        return fn

    def barrier(self, name, host_id, timeout_s=None):
        """Block until every live host reaches the same ``name``;
        returns the sorted ids that arrived."""
        got = self.all_gather("barrier:%s" % name, host_id,
                              timeout_s=timeout_s)
        return sorted(got)

    def elect_restore_step(self, host_id, valid_steps, name="elect",
                           quorum=None, timeout_s=None):
        """Consensus restore step for the whole pod.

        Every live host contributes the steps its checkpoint scrub
        validated (``io.scrub_checkpoint(dir)["valid_steps"]``); the
        consensus is the MAX step reported by at least ``quorum`` live
        hosts — default ALL of them, because with per-host checkpoint
        dirs every host must hold the step it is told to restore. On a
        shared filesystem (one dir scrubbed by everyone) a smaller
        quorum tolerates scrub-time races. Deterministic: every host
        computes the same answer from the same gathered sets.

        Raises :class:`NoQuorumError` when no step qualifies."""
        got = self.all_gather("elect:%s" % name, host_id,
                              sorted(int(s) for s in set(valid_steps)),
                              timeout_s=timeout_s)
        counts = collections.Counter(
            s for steps in got.values() for s in steps)
        need = len(got) if quorum is None else min(int(quorum), len(got))
        eligible = [s for s, c in counts.items() if c >= need]
        if not eligible:
            raise NoQuorumError(
                "no checkpoint step is valid on %d/%d live hosts "
                "(reported: %s) — nothing the pod can agree to restore"
                % (need, len(got),
                   {h: list(v) for h, v in sorted(got.items())}))
        step = max(eligible)
        record_event("consensus", step=step, hosts=len(got),
                     quorum=need)
        return step

    def _on_loss(self, newly_lost):
        """Fan out a host-loss: resilience event, mesh re-init, hooks."""
        if not newly_lost:
            return
        live = self.live_hosts()
        record_event("host_lost", hosts=sorted(newly_lost),
                     live=list(live))
        if self._mesh_reinit:
            from ..distributed import mesh as mesh_mod
            mesh_mod.handle_host_loss(sorted(self.lost_hosts()), live)
        for fn in list(self._loss_hooks):
            fn(sorted(newly_lost), live)


# ---------------------------------------------------------------------------
# in-process (threaded) coordinator
# ---------------------------------------------------------------------------

class LocalCoordinator(Coordinator):
    """Thread-based coordinator: N logical hosts in one process.

    This is the tier-1 test vehicle — it runs the exact consensus and
    fencing logic of the pod control plane with no processes, sockets or
    real TPUs, which is how the chaos battery stays fast and
    deterministic."""

    def __init__(self, n_hosts, timeout_s=30.0, detect_loss=True,
                 mesh_reinit=True):
        super(LocalCoordinator, self).__init__(
            n_hosts, timeout_s=timeout_s, detect_loss=detect_loss,
            mesh_reinit=mesh_reinit)
        self._cond = threading.Condition()
        self._lost = {}
        self._rounds = {}   # name -> {"values": {hid: v}, "exits": int}

    def live_hosts(self):
        with self._cond:
            return [i for i in range(self.n_hosts) if i not in self._lost]

    def lost_hosts(self):
        with self._cond:
            return dict(self._lost)

    def mark_lost(self, host_id, reason="declared lost"):
        with self._cond:
            if host_id in self._lost:
                return
            self._lost[host_id] = reason
            self._cond.notify_all()
        self._on_loss([host_id])

    def all_gather(self, name, host_id, value=None, timeout_s=None):
        deadline = time.monotonic() + (self.timeout_s if timeout_s is None
                                       else float(timeout_s))
        newly_lost = []
        with self._cond:
            if host_id in self._lost:
                raise HostLostError(
                    "host %d is fenced (%s) — rejoin, don't resume"
                    % (host_id, self._lost[host_id]))
            r = self._rounds.setdefault(name, {"values": {}, "exits": 0})
            if host_id in r["values"]:
                raise CoordinationError(
                    "host %d already contributed to round %r — collective "
                    "names must be unique per round" % (host_id, name))
            r["values"][host_id] = value
            self._cond.notify_all()
            while True:
                waiting_for = [i for i in range(self.n_hosts)
                               if i not in self._lost
                               and i not in r["values"]]
                if not waiting_for:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if not self.detect_loss:
                        raise BarrierTimeoutError(
                            "round %r timed out waiting for hosts %s"
                            % (name, waiting_for))
                    for i in waiting_for:
                        self._lost[i] = "missed round %r" % name
                        newly_lost.append(i)
                    self._cond.notify_all()
                    continue
                self._cond.wait(remaining)
            if host_id in self._lost:
                # marked lost while blocked in this very round: fence
                raise HostLostError(
                    "host %d is fenced (%s) — rejoin, don't resume"
                    % (host_id, self._lost[host_id]))
            result = {i: v for i, v in r["values"].items()
                      if i not in self._lost}
            r["exits"] += 1
            if r["exits"] >= len(result):
                self._rounds.pop(name, None)   # last one out cleans up
        # hooks run OUTSIDE the lock: mesh re-init is arbitrary user code
        self._on_loss(newly_lost)
        return result


# ---------------------------------------------------------------------------
# file-based coordinator (multi-process pods on a shared filesystem)
# ---------------------------------------------------------------------------

class FileCoordinator(Coordinator):
    """Coordinator over a shared directory — one object per PROCESS.

    All state flows through atomically-committed files (io._atomic_write
    discipline: temp file + os.replace), so N processes that share only
    a filesystem agree exactly like LocalCoordinator's threads:

        <root>/lost/host_<i>              tombstone (fence), reason text
        <root>/rounds/<name>/host_<i>.json   one contribution per round

    Polling (``poll_s``) replaces condition variables; round names must
    be unique per live round exactly as with LocalCoordinator
    (PodResilientTrainer namespaces every round by a per-run counter).
    The last host to read a completed round removes its directory, so
    the rounds dir stays bounded over a long job. A RESTARTED process
    must rejoin on a fresh coordinator root as a new participant — its
    old incarnation is fenced, and replaying old round names against a
    stale root would read stale contributions."""

    def __init__(self, root, n_hosts, timeout_s=30.0, poll_s=0.01,
                 detect_loss=True, mesh_reinit=True):
        super(FileCoordinator, self).__init__(
            n_hosts, timeout_s=timeout_s, detect_loss=detect_loss,
            mesh_reinit=mesh_reinit)
        import os
        self._root = root
        self._lost_dir = os.path.join(root, "lost")
        self._rounds_dir = os.path.join(root, "rounds")
        self.poll_s = float(poll_s)
        # per-PROCESS loss knowledge: tombstones written by peers must
        # fire THIS process's _on_loss (mesh re-init is per-process
        # state) exactly once, whoever won the race to write them
        self._known_lost = set()
        os.makedirs(self._lost_dir, exist_ok=True)
        os.makedirs(self._rounds_dir, exist_ok=True)

    @staticmethod
    def _safe(name):
        return "".join(c if (c.isalnum() or c in "._-") else "_"
                       for c in name)

    def lost_hosts(self):
        import os
        out = {}
        for f in os.listdir(self._lost_dir):
            if f.startswith("host_"):
                try:
                    with open(os.path.join(self._lost_dir, f)) as fh:
                        out[int(f[5:])] = fh.read().strip()
                except (OSError, ValueError):   # pragma: no cover - race
                    continue
        return out

    def live_hosts(self):
        lost = self.lost_hosts()
        return [i for i in range(self.n_hosts) if i not in lost]

    def mark_lost(self, host_id, reason="declared lost"):
        import os
        from ..io import _atomic_write
        if host_id in self.lost_hosts():
            return
        _atomic_write(os.path.join(self._lost_dir, "host_%d" % host_id),
                      reason)
        self._known_lost.add(host_id)
        self._on_loss([host_id])

    def all_gather(self, name, host_id, value=None, timeout_s=None):
        import json
        import os
        from ..io import _atomic_write
        deadline = time.monotonic() + (self.timeout_s if timeout_s is None
                                       else float(timeout_s))
        rd = os.path.join(self._rounds_dir, self._safe(name))
        os.makedirs(rd, exist_ok=True)
        lost = self.lost_hosts()
        if host_id in lost:
            raise HostLostError(
                "host %d is fenced (%s) — rejoin, don't resume"
                % (host_id, lost[host_id]))
        mine = os.path.join(rd, "host_%d.json" % host_id)
        if os.path.exists(mine):
            # same split-brain guard as LocalCoordinator: never let an
            # imposter (or a replayed round name) overwrite a live value
            raise CoordinationError(
                "host %d already contributed to round %r — collective "
                "names must be unique per round" % (host_id, name))
        _atomic_write(mine, json.dumps({"value": value}))
        while True:
            lost = self.lost_hosts()
            present = {int(f[5:-5]) for f in os.listdir(rd)
                       if f.startswith("host_") and f.endswith(".json")}
            waiting_for = [i for i in range(self.n_hosts)
                           if i not in lost and i not in present]
            if not waiting_for:
                break
            if time.monotonic() >= deadline:
                if not self.detect_loss:
                    raise BarrierTimeoutError(
                        "round %r timed out waiting for hosts %s"
                        % (name, waiting_for))
                for i in waiting_for:
                    # first tombstone wins; duplicates are idempotent —
                    # _on_loss firing is keyed on _known_lost below, so
                    # losing this race still re-inits OUR mesh
                    if i not in self.lost_hosts():
                        _atomic_write(
                            os.path.join(self._lost_dir, "host_%d" % i),
                            "missed round %r" % name)
                continue
            time.sleep(self.poll_s)
        lost = self.lost_hosts()
        if host_id in lost:
            raise HostLostError(
                "host %d is fenced (%s) — rejoin, don't resume"
                % (host_id, lost[host_id]))
        result = {}
        for i in sorted(present - set(lost)):
            with open(os.path.join(rd, "host_%d.json" % i)) as fh:
                result[i] = json.load(fh)["value"]
        # last one out cleans up (LocalCoordinator parity): every value
        # is written before any ack, and removal needs every reader's
        # ack — so nobody can lose a file they still need. Lost hosts
        # never ack; their rounds leak, bounded by the loss count.
        _atomic_write(os.path.join(rd, "ack_%d" % host_id), "")
        try:
            acked = {int(f[4:]) for f in os.listdir(rd)
                     if f.startswith("ack_")}
            if acked >= set(result):
                import shutil
                shutil.rmtree(rd, ignore_errors=True)
        except (OSError, ValueError):   # pragma: no cover - lost race
            pass
        # fire for every loss THIS process has not yet reacted to —
        # including tombstones another process won the race to write:
        # mesh re-init is per-process state, so a survivor that merely
        # OBSERVES a loss must still rebuild its collectives
        newly_observed = sorted(set(lost) - self._known_lost)
        self._known_lost.update(lost)
        self._on_loss(newly_observed)
        return result


# ---------------------------------------------------------------------------
# pod-level resilient training
# ---------------------------------------------------------------------------

class PodResilientTrainer(object):
    """Coordinated auto-recovery across an N-host pod.

    Wraps N per-host :class:`~.resilience.ResilientTrainer` s — each
    with its own executor, Scope and checkpoint dir. In production every
    host process builds exactly one trainer and they meet on a
    :class:`FileCoordinator`; in tests all N live in one process on a
    :class:`LocalCoordinator` (threads), which exercises the identical
    consensus protocol.

    Protocol, per dispatch window:

      1. every host dispatches its window and (at a checkpoint boundary)
         saves its shards;
      2. status exchange (all_gather): ok / transient / fatal;
      3. all ok -> commit and continue. Any fatal -> the whole pod
         aborts (a shape bug replays identically — retrying burns the
         budget on every host). Any transient -> pod-wide recovery:
         every host scrubs its checkpoint dir WITHOUT loading payloads
         (io.scrub_checkpoint), the coordinator elects the max step
         validated on every live host, and every host restores exactly
         that step (io.load_checkpoint(step=...): no silent fallback —
         a mismatched restore would deadlock the collectives).

    Because each host's checkpoint carries params, optimizer moments AND
    the PRNG step counter, the replayed pod trajectory is bitwise
    identical to a fault-free run. The restart budget is SHARED: rewinds
    are pod-wide, so every host's counter advances in lockstep and the
    pod gives up together with RestartBudgetExceededError.
    """

    def __init__(self, trainers, coordinator=None, max_restarts=3,
                 host_id=None):
        """``host_id=None`` (simulation): ``trainers`` holds ALL N hosts
        and run() drives them on N threads. ``host_id=i`` (production,
        one process per host): ``trainers`` holds exactly THIS host's
        trainer, ``coordinator`` is the shared rendezvous (e.g. a
        FileCoordinator over a common root with ``n_hosts`` = pod size),
        and run() drives the single host loop in the calling thread —
        its peers are other processes, not threads."""
        if not trainers:
            raise ValueError("PodResilientTrainer needs >= 1 trainer")
        self._trainers = list(trainers)
        every = {t._checkpoint_every for t in self._trainers}
        window = {t._steps_per_dispatch for t in self._trainers}
        keep = {t._keep_last for t in self._trainers}
        if len(every) != 1 or len(window) != 1 or len(keep) != 1:
            # the recovery protocol assumes identical control flow on
            # every host: same windows, same checkpoint boundaries,
            # same pruning horizon
            raise ValueError(
                "all pod trainers must agree on checkpoint_every, "
                "steps_per_dispatch and keep_last (got %s / %s / %s)"
                % (sorted(every), sorted(window), sorted(keep)))
        if min(keep) < 2:
            # a host that faulted BEFORE the window's save holds one
            # fewer checkpoint than its ok peers; keep_last=1 would let
            # the peers prune the last step everyone shares, turning a
            # recoverable transient into a NoQuorumError cold start
            raise ValueError(
                "pod trainers need keep_last >= 2: the consensus "
                "election requires the previous common checkpoint to "
                "survive the ok hosts' pruning")
        self._coordinator = coordinator or LocalCoordinator(
            len(self._trainers))
        self._host_id = None if host_id is None else int(host_id)
        if self._host_id is None:
            if self._coordinator.n_hosts != len(self._trainers):
                raise ValueError(
                    "coordinator expects %d hosts but %d trainers were "
                    "given" % (self._coordinator.n_hosts,
                               len(self._trainers)))
        else:
            if len(self._trainers) != 1:
                raise ValueError(
                    "host_id mode is one-process-per-host: pass exactly "
                    "this host's trainer (got %d)" % len(self._trainers))
            if not 0 <= self._host_id < self._coordinator.n_hosts:
                raise ValueError(
                    "host_id %d out of range for a %d-host coordinator"
                    % (self._host_id, self._coordinator.n_hosts))
        self._max_restarts = int(max_restarts)
        # advances once per run() on EVERY host (runs are lockstep like
        # everything else), namespacing round names so a second run()
        # on the same coordinator never collides with the first's rounds
        self._run_seq = 0

    @property
    def coordinator(self):
        return self._coordinator

    def run(self, feeds, fetch_list=None):
        """Run the pod to completion, recovering from transient faults.

        ``feeds``: either ONE list of per-step feed dicts (replicated to
        every host — the data-parallel-replica shape) or a list of N
        per-host feed lists of EQUAL length (each host trains its own
        stream). Returns the per-host fetch lists ``[n_hosts][n_steps]``.

        In ``host_id`` mode feeds is THIS host's list of per-step feed
        dicts and the return value is its fetch list ``[n_steps]`` —
        the peers run the same call in their own processes.
        """
        from . import resilience
        if self._host_id is not None:
            self._run_seq += 1
            with resilience.context(host=self._host_id):
                return self._host_loop(self._host_id,
                                       "r%d." % self._run_seq,
                                       list(feeds), fetch_list)
        n_hosts = len(self._trainers)
        if not feeds or isinstance(feeds[0], dict):
            per_host = [list(feeds)] * n_hosts
        else:
            per_host = [list(f) for f in feeds]
            if len(per_host) != n_hosts:
                raise ValueError(
                    "per-host feeds: expected %d lists, got %d"
                    % (n_hosts, len(per_host)))
        if len({len(f) for f in per_host}) > 1:
            raise ValueError("every host needs the same number of steps "
                             "(lockstep collectives)")
        results = [None] * n_hosts
        errors = [None] * n_hosts
        self._run_seq += 1
        run_tag = "r%d." % self._run_seq

        def host_main(hid):
            from . import resilience
            try:
                with resilience.context(host=hid):
                    results[hid] = self._host_loop(hid, run_tag,
                                                   per_host[hid],
                                                   fetch_list)
            except BaseException as e:   # surfaced after join
                errors[hid] = e

        threads = [threading.Thread(target=host_main, args=(hid,),
                                    name="pod-host-%d" % hid)
                   for hid in range(n_hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        real = [e for e in errors
                if e is not None and not isinstance(e, CoordinationError)]
        if real:
            raise real[0]
        coord = [e for e in errors if e is not None]
        if coord:
            raise coord[0]
        return results

    def _host_loop(self, hid, run_tag, feeds, fetch_list):
        # host_id mode holds only THIS host's trainer; simulation mode
        # holds all of them, indexed by the logical host id
        trainer = self._trainers[0] if self._host_id is not None \
            else self._trainers[hid]
        co = self._coordinator
        fetch_list = trainer._resolved_fetch_list(fetch_list)
        n = len(feeds)
        trainer._require_fresh_dir()
        trainer._save(0)
        co.barrier(run_tag + "pod_start", hid)
        if n == 0:
            co.barrier(run_tag + "pod_end", hid)
            return []
        all_fetches = [None] * n
        ckpt_every = trainer._checkpoint_every
        step, restarts, rnd = 0, 0, 0
        while step < n:
            rnd += 1   # advances identically on every host: round names
            #            line up without any out-of-band numbering
            until_ckpt = ckpt_every - (step % ckpt_every)
            w = min(trainer._steps_per_dispatch, n - step, until_ckpt)
            status, err, outs = "ok", None, None
            try:
                outs = trainer._dispatch(feeds, step, w, fetch_list)
                if (step + w) % ckpt_every == 0 or step + w == n:
                    trainer._save(step + w)
            except Exception as e:
                err = e
                status = "transient" if trainer._policy.is_transient(e) \
                    else "fatal"
            verdicts = co.all_gather("%sw%d" % (run_tag, rnd), hid,
                                     status)
            if any(v == "fatal" for v in verdicts.values()):
                record_event("fatal", step=step,
                             error=type(err).__name__ if err else None)
                if err is not None and status == "fatal":
                    raise err
                bad = sorted(h for h, v in verdicts.items()
                             if v == "fatal")
                raise CoordinationError(
                    "pod aborted: host(s) %s hit a fatal error at step %d"
                    % (bad, step))
            if all(v == "ok" for v in verdicts.values()):
                for i in range(w):
                    all_fetches[step + i] = outs[i]
                step += w
                continue
            # -- pod-wide recovery ------------------------------------
            restarts += 1   # lockstep on every host: the SHARED budget
            if restarts > self._max_restarts:
                record_event("giveup", step=step, restarts=restarts)
                raise RestartBudgetExceededError(
                    "pod restart budget (%d) exhausted at step %d; "
                    "last local error: %r" % (self._max_restarts, step,
                                              err))
            delay = trainer._policy.delay_s(restarts - 1)
            record_event("pod_restart", step=step, restarts=restarts,
                         error=type(err).__name__ if err else None,
                         backoff_s=delay)
            trainer._policy.sleep(delay)
            from .. import io as io_mod
            report = io_mod.scrub_checkpoint(trainer._ckpt_dir)
            agreed = co.elect_restore_step(hid, report["valid_steps"],
                                           name="%se%d" % (run_tag, rnd))
            got = trainer._restore(step=agreed)
            record_event("pod_restore", step=got)
            step = got
        co.barrier(run_tag + "pod_end", hid)
        return all_fetches
