"""Pod recovery control plane — agreed restores for multi-host training.

Reference parity: the reference stack recovers pserver fleets as a UNIT
(`operators/distributed` + fleet roles: trainers reconnect, pservers
re-serve tables, the whole job restarts from one snapshot). On TPU there
is no pserver tier — the ICI collectives that replace the RPC layer
(psum/all_gather inside the jitted step) deadlock if any host resumes at
a different step than its peers, so recovery must be AGREED: either
every host rewinds to one quorum-validated checkpoint step, or none
does. framework/resilience.py closes the detect->recover loop for ONE
process; this module is the pod half:

  * :class:`Coordinator` — the contract: ``barrier`` / ``all_gather`` /
    ``elect_restore_step`` (consensus = max step for which a
    scrub-validated checkpoint exists on every live host), plus
    host-loss detection that fires mesh re-initialization hooks
    (distributed/mesh.py) so survivors rebuild collectives without the
    dead host.
  * :class:`LocalCoordinator` — in-process, thread-based. Drives tier-1
    tests and single-process simulations of an N-host pod (the ``pod``
    pytest marker).
  * :class:`FileCoordinator` — file-based, for real multi-process pods
    sharing a filesystem. Every contribution is an atomic file write;
    no shared memory, so N processes each owning one FileCoordinator
    object agree through the directory alone.
  * :class:`SocketCoordinator` — network-based, for real multi-process
    pods WITHOUT shared storage (the reference's pserver/brpc shape).
    The coordination KV state lives in a stdlib-TCP rendezvous service
    (framework/transport.py, deployable via ``tools/coordsvc.py``);
    liveness is real — clients heartbeat the server and a missed
    deadline tombstones the host, no declaration needed.
  * :class:`PodResilientTrainer` — wraps N per-host
    :class:`~.resilience.ResilientTrainer` s. Every dispatch window ends
    in a status exchange; if ANY host saw a transient fault, every host
    scrubs its checkpoint dir (``io.scrub_checkpoint`` — manifest +
    shard headers, never array payloads), the coordinator elects the
    consensus step, and ALL hosts restore it and replay. The replayed
    trajectory is bitwise-identical to a fault-free run, and the
    restart budget is shared: rewinds are pod-wide, so every host's
    budget counter advances in lockstep.
"""
import collections
import threading
import time

from . import obs
from . import resilience
from .resilience import RestartBudgetExceededError, record_event

__all__ = [
    "CoordinationError", "HostLostError", "BarrierTimeoutError",
    "NoQuorumError", "Coordinator", "LocalCoordinator",
    "FileCoordinator", "SocketCoordinator", "PodResilientTrainer",
    "ElasticTrainer", "agreed_pending",
]

# the fence reason dynamic resize stamps on a GROWN slot: the member
# has never joined, so observers must not treat the tombstone as a
# host LOSS (no loss hooks, no host_lost event, no mesh re-init) —
# it clears through the ordinary announce/admit/join path instead
GROW_FENCE_REASON = "resized: awaiting join"


def agreed_pending(verdicts, idx=1):
    """The admission ``[host, nonce]`` pair EVERY participant of a
    frozen gather observed — the first such pair in the lowest live
    host's ordering, or None. Each verdict's ``idx`` element is that
    host's sorted view of the pending-join set.

    This is the agreement invariant that makes the join barrier
    complete: because it is computed from the same frozen verdicts on
    every host, all of them admit the SAME joiner together. Shared by
    :class:`ElasticTrainer`'s window-boundary admission and the
    serving fleet's control rounds — it must have exactly one
    definition."""
    live = sorted(verdicts)
    for pair in (verdicts[live[0]][idx] if live else []):
        if all(pair in v[idx] for v in verdicts.values()):
            return pair
    return None


class CoordinationError(RuntimeError):
    """A pod-level coordination failure (peer fatal, protocol misuse)."""


class HostLostError(CoordinationError):
    """This host was marked lost (fenced): it missed a barrier or was
    declared dead. A fenced host must NOT keep training — rejoin via the
    orchestrator as a fresh participant instead of split-braining."""


class BarrierTimeoutError(CoordinationError):
    """A collective did not complete in time and loss detection was
    disabled, so nobody was marked lost — the caller decides."""


class NoQuorumError(CoordinationError):
    """No checkpoint step is valid on enough live hosts to restore —
    escalate to the orchestrator (cold start or manual repair)."""


class BlobTooLargeError(CoordinationError):
    """A legacy-mode ``put_blob`` payload exceeded the coordinator's
    ``blob_max_bytes`` ceiling. Named so a misconfigured pod fails
    TYPED (the buddy tier records buddy_send_fail and training keeps
    the disk fallback) instead of silently growing the coordinator
    process until the OOM killer fences the whole control plane. The
    p2p mailbox tier has no such ceiling — payloads live in peer
    host RAM."""


# ---------------------------------------------------------------------------
# coordinator contract + shared consensus logic
# ---------------------------------------------------------------------------

class Coordinator(object):
    """Base contract. Subclasses implement :meth:`all_gather` plus the
    live/lost bookkeeping; everything else (barrier, consensus election,
    host-loss hook fan-out) is shared.

    Host-loss semantics: when a collective times out, the hosts that
    never arrived are marked LOST (``detect_loss=True``), the remaining
    values are returned to the survivors, and the loss hooks fire —
    including mesh re-initialization (``distributed.mesh
    .handle_host_loss``) so the survivors' collectives are rebuilt
    without the dead host. A lost host that later calls in gets
    :class:`HostLostError` (fencing: it must rejoin, not resume).
    """

    def __init__(self, n_hosts, timeout_s=30.0, detect_loss=True,
                 mesh_reinit=True):
        if n_hosts < 1:
            raise ValueError("n_hosts must be >= 1")
        self.n_hosts = int(n_hosts)
        self.timeout_s = float(timeout_s)
        self.detect_loss = bool(detect_loss)
        self._mesh_reinit = bool(mesh_reinit)
        self._loss_hooks = []
        self._join_hooks = []
        # admissions THIS object already reacted to: LocalCoordinator is
        # shared by every simulated host, so the mesh re-grows once; a
        # FileCoordinator is per-process, so every process re-grows its
        # own mesh — same guard, right semantics in both topologies
        self._absorbed = set()
        self._absorb_lock = threading.Lock()
        # buddy-snapshot mailboxes, default in-memory store (Local
        # shares ONE coordinator object across simulated hosts, so the
        # store is naturally pod-wide; File is per-process, so a dead
        # host's mailbox is simply absent there and restores fall back
        # to disk). SocketCoordinator overrides put_blob/get_blob to
        # keep the mailboxes on the CoordServer instead.
        self._blobs = {}
        self._blob_lock = threading.Lock()
        # legacy put_blob payload ceiling (None = unbounded, the
        # in-process default; CoordServer enforces its own finite one)
        self.blob_max_bytes = None
        # p2p buddy tier: per-host BuddyMailbox registry + the
        # {owner: (gen, buddy, digest, nbytes)} metadata table. Same
        # topology note as _blobs — Local's shared object makes the
        # registry pod-wide (deposits really land in "the other
        # host's" mailbox), File's per-process registry degrades every
        # restore to buddy_missing. SocketCoordinator overrides the
        # mailbox_*/put_buddy_meta surface to run a real per-host
        # MailboxServer endpoint and keep the metadata on the
        # CoordServer.
        self._mailboxes = {}
        self._buddy_meta = {}
        self._mailbox_lock = threading.Lock()

    # -- subclass surface --------------------------------------------------
    def all_gather(self, name, host_id, value=None, timeout_s=None):
        """Collective: every live host contributes ``value`` under the
        (round-unique) ``name``; returns {host_id: value} of the live
        participants. Blocks until all live hosts arrive or the timeout
        handles the missing ones (see class docstring)."""
        raise NotImplementedError

    def live_hosts(self):
        raise NotImplementedError

    def lost_hosts(self):
        """{host_id: reason} of every host marked lost so far."""
        raise NotImplementedError

    def mark_lost(self, host_id, reason="declared lost"):
        raise NotImplementedError

    def announce_join(self, host_id, nonce):
        """A FENCED host announces it wants back in. ``nonce`` is the
        host's rejoin-attempt counter — it namespaces the admission
        round so the same host can rejoin repeatedly. Raises
        CoordinationError for a host that is not fenced (a live host
        has nothing to rejoin)."""
        raise NotImplementedError

    def pending_joins(self):
        """{host_id: nonce} of fenced hosts waiting for admission."""
        raise NotImplementedError

    def unfence(self, host_id):
        """Clear ``host_id``'s tombstone and join request (idempotent).
        Only the admission path may call this — un-fencing a host that
        did not go through :meth:`admit`/:meth:`join` recreates exactly
        the split brain fencing exists to prevent."""
        raise NotImplementedError

    def resize(self, n_hosts):
        """DYNAMIC GROUP RESIZE: change the group size at a round
        boundary. Grown slots are born FENCED ("resized: awaiting
        join") so no in-flight gather ever waits for a member that has
        not joined — the new member's start finds itself fenced and
        takes the ordinary announce/admit/join path. A shrink only
        removes TOP ids that are already fenced (drain first); raises
        :class:`CoordinationError` for the protocol's named refusals
        (a mid-round call, a live id in the shrink range) and
        ``ValueError`` for n_hosts < 1. Returns the new size."""
        raise NotImplementedError

    @staticmethod
    def _check_resize(n_hosts, current, open_rounds, live_in_range):
        """Shared resize validation; returns the int size to adopt."""
        n = int(n_hosts)
        if n < 1:
            raise ValueError("resize: n_hosts must be >= 1, got %d" % n)
        if open_rounds:
            raise CoordinationError(
                "resize refused mid-round: gather round(s) %s in "
                "flight — retry at a round boundary"
                % sorted(open_rounds)[:3])
        if n < current and live_in_range:
            raise CoordinationError(
                "resize refused: host(s) %s still live — drain/fence "
                "them before shrinking past their ids"
                % sorted(live_in_range))
        return n

    # -- shared machinery --------------------------------------------------
    def add_host_loss_hook(self, fn):
        """Register ``fn(lost_ids, live_ids)`` to run on host loss (after
        the built-in mesh re-init). Returns fn for decorator use."""
        self._loss_hooks.append(fn)
        return fn

    def add_host_join_hook(self, fn):
        """Register ``fn(joined_ids, live_ids)`` to run when a host is
        re-absorbed (after the built-in mesh re-grow). Returns fn."""
        self._join_hooks.append(fn)
        return fn

    def admit(self, host_id, joined, nonce, value, name="join",
              timeout_s=None, enact=True, poll_s=0.01):
        """Survivor half of the rejoin protocol.

        Every SURVIVOR calls this in the same window (the pending-join
        set must be agreed out of band — ElasticTrainer rides it on the
        window status exchange, so all hosts compute the same admission
        deterministically). It un-fences ``joined`` (idempotent across
        survivors), then meets the joiner on the admission barrier,
        contributing ``value`` — the survivor's sync coordinates (step
        counter etc.); the joiner contributes None and adopts the max.
        After the barrier the mesh re-absorbs the host
        (:func:`distributed.mesh.absorb_hosts`) and join hooks fire.

        ``enact=False`` is the FOLLOWER half of leader-based admission
        (the serving fleet's router tier): the caller meets the
        admission barrier but does NOT un-fence — it waits (bounded by
        the timeout) for the admission LEADER's un-fence to land
        first, so the barrier can never freeze without the joiner.
        Returns None when the leader never enacted in time.

        Returns the agreed sync value, or None when the joiner died
        between announcing and the barrier (it is re-fenced by the
        barrier timeout and the admission is abandoned)."""
        with obs.span("coord.admit", joined=joined, host=host_id,
                      enact=bool(enact)):
            return self._admit_traced(host_id, joined, nonce, value,
                                      name, timeout_s, enact, poll_s)

    def _admit_traced(self, host_id, joined, nonce, value, name,
                      timeout_s, enact, poll_s):
        if enact:
            self.unfence(joined)
        else:
            deadline = time.monotonic() + (
                self.timeout_s if timeout_s is None
                else float(timeout_s))
            while joined in self.lost_hosts():
                if time.monotonic() >= deadline:
                    record_event("join_abort", host=joined, nonce=nonce,
                                 reason="admission leader never "
                                 "enacted")
                    return None
                time.sleep(poll_s)
        round_name = "%s:h%d:n%d" % (name, joined, nonce)
        got = self.all_gather(round_name, host_id, value,
                              timeout_s=timeout_s)
        if joined not in got:
            record_event("join_abort", host=joined, nonce=nonce)
            return None
        sync = max(v for v in got.values() if v is not None)
        self._on_join([joined], nonce, sync)
        return sync

    def join(self, host_id, nonce, name="join", timeout_s=None,
             poll_s=0.01):
        """Joiner half: after :meth:`announce_join`, block until the
        survivors un-fence this host, then meet the admission barrier.
        Returns the survivors' agreed sync value. Raises
        BarrierTimeoutError when no admission lands in time (the host
        stays fenced — escalate to the orchestrator)."""
        with obs.span("coord.join", host=host_id):
            deadline = time.monotonic() + (
                self.timeout_s if timeout_s is None
                else float(timeout_s))
            while host_id in self.lost_hosts():
                if time.monotonic() >= deadline:
                    raise BarrierTimeoutError(
                        "host %d announced a rejoin but was not "
                        "admitted in time — survivors may be "
                        "mid-recovery or gone" % host_id)
                time.sleep(poll_s)
            round_name = "%s:h%d:n%d" % (name, host_id, nonce)
            got = self.all_gather(round_name, host_id, None,
                                  timeout_s=timeout_s)
            values = [v for v in got.values() if v is not None]
            if not values:
                raise CoordinationError(
                    "admission round %r carried no sync value from "
                    "any survivor" % round_name)
            sync = max(values)
            self._on_join([host_id], nonce, sync)
            return sync

    def _on_join(self, joined, nonce, sync):
        """Fan out an admission exactly once per coordinator object:
        resilience event, mesh re-grow, join hooks."""
        key = (tuple(joined), int(nonce))
        with self._absorb_lock:
            if key in self._absorbed:
                return
            self._absorbed.add(key)
        live = self.live_hosts()
        record_event("host_join", hosts=sorted(joined), live=list(live),
                     sync=sync)
        if self._mesh_reinit:
            from ..distributed import mesh as mesh_mod
            mesh_mod.absorb_hosts(sorted(joined), live)
        for fn in list(self._join_hooks):
            fn(sorted(joined), live)

    def barrier(self, name, host_id, timeout_s=None):
        """Block until every live host reaches the same ``name``;
        returns the sorted ids that arrived."""
        got = self.all_gather("barrier:%s" % name, host_id,
                              timeout_s=timeout_s)
        return sorted(got)

    def elect_restore_step(self, host_id, valid_steps, name="elect",
                           quorum=None, timeout_s=None):
        """Consensus restore step for the whole pod.

        Every live host contributes the steps its checkpoint scrub
        validated (``io.scrub_checkpoint(dir)["valid_steps"]``); the
        consensus is the MAX step reported by at least ``quorum`` live
        hosts — default ALL of them, because with per-host checkpoint
        dirs every host must hold the step it is told to restore. On a
        shared filesystem (one dir scrubbed by everyone) a smaller
        quorum tolerates scrub-time races. Deterministic: every host
        computes the same answer from the same gathered sets.

        Raises :class:`NoQuorumError` when no step qualifies."""
        got = self.all_gather("elect:%s" % name, host_id,
                              sorted(int(s) for s in set(valid_steps)),
                              timeout_s=timeout_s)
        counts = collections.Counter(
            s for steps in got.values() for s in steps)
        need = len(got) if quorum is None else min(int(quorum), len(got))
        eligible = [s for s, c in counts.items() if c >= need]
        if not eligible:
            raise NoQuorumError(
                "no checkpoint step is valid on %d/%d live hosts "
                "(reported: %s) — nothing the pod can agree to restore"
                % (need, len(got),
                   {h: list(v) for h, v in sorted(got.items())}))
        step = max(eligible)
        record_event("consensus", step=step, hosts=len(got),
                     quorum=need)
        return step

    # -- buddy-snapshot mailboxes (framework/buddy.py rides these) --------
    def put_blob(self, host_id, gen, buddy, blob, reset=False):
        """Store ``host_id``'s buddy snapshot. ONE generation is kept
        per owner (bounded memory): a higher ``gen`` overwrites in
        place, the same ``gen`` is an idempotent re-send, and a LOWER
        one raises CoordinationError — a delayed put must never rewind
        the mailbox below what a restore may already have adopted.
        ``reset=True`` force-overwrites regardless of generation: the
        post-disk-restore re-seed, where the pod legitimately rewound
        below the mailbox gen (and a poison-batch replay may change
        the trajectory, making even an equal-gen blob stale)."""
        gen, owner = int(gen), int(host_id)
        if owner in self.lost_hosts():
            raise HostLostError(
                "host %d is fenced — a fenced host must not publish "
                "buddy snapshots" % owner)
        if self.blob_max_bytes is not None:
            nb = len(blob.get("npz", "")) if isinstance(blob, dict) \
                else (0 if blob is None else len(str(blob)))
            if nb > self.blob_max_bytes:
                raise BlobTooLargeError(
                    "put_blob of %d bytes for host %d exceeds the "
                    "coordinator's blob_max_bytes=%d ceiling — use "
                    "the p2p mailbox tier for scopes this size"
                    % (nb, owner, self.blob_max_bytes))
        with self._blob_lock:
            prev = self._blobs.get(owner)
            if reset:
                self._blobs[owner] = {"gen": gen, "buddy": int(buddy),
                                      "blob": blob}
                return
            if prev is not None and gen < prev["gen"]:
                raise CoordinationError(
                    "put_blob generation rewind: host %d is at gen %d, "
                    "refused gen %d" % (owner, prev["gen"], gen))
            if prev is None or gen > prev["gen"]:
                self._blobs[owner] = {"gen": gen, "buddy": int(buddy),
                                      "blob": blob}

    def get_blob(self, owner, meta_only=False):
        """Fetch ``owner``'s buddy snapshot record
        ``{"gen", "buddy"[, "blob"]}`` or None when no mailbox exists
        (``meta_only=True`` skips the payload — the restore election
        polls generations cheaply). Read-only and unfenced: a fenced
        survivor reading its own last snapshot IS the restore path."""
        with self._blob_lock:
            rec = self._blobs.get(int(owner))
            if rec is None:
                return None
            out = {"gen": rec["gen"], "buddy": rec["buddy"]}
            if not meta_only:
                out["blob"] = rec["blob"]
            return out

    # -- p2p buddy mailboxes + metadata table -----------------------------
    def mailbox_of(self, host_id):
        """``host_id``'s :class:`buddy.BuddyMailbox`, created on first
        touch. In the base (in-process) plane the registry is shared
        by every host the coordinator object serves."""
        from . import buddy as buddy_mod
        hid = int(host_id)
        with self._mailbox_lock:
            mb = self._mailboxes.get(hid)
            if mb is None:
                mb = self._mailboxes[hid] = \
                    buddy_mod.BuddyMailbox(host_id=hid)
            return mb

    def mailbox_send(self, owner, at, payload):
        """Deposit ``owner``'s payload into host ``at``'s mailbox and
        return the mailbox's ack/refusal dict. ``at == owner`` is the
        free local self-deposit; anything else models the p2p stream
        (a real one over MailboxServer in the socket plane)."""
        return self.mailbox_of(at).deposit(owner, payload)

    def mailbox_fetch(self, owner, at):
        """Reconstruct ``owner``'s resident generation out of host
        ``at``'s mailbox: ``{"gen", "digest", "blob"}``, or None when
        the mailbox/slot is absent. Raises on chain/digest corruption
        — the buddy tier maps every raise to ``snapshot_torn``."""
        with self._mailbox_lock:
            mb = self._mailboxes.get(int(at))
        if mb is None:
            return None
        try:
            return mb.reconstruct(owner)
        except LookupError:
            return None

    def put_buddy_meta(self, host_id, gen, buddy, digest, nbytes,
                       reset=False):
        """Commit ``host_id``'s metadata row ``{gen, buddy, digest,
        nbytes}`` — called ONLY after the buddy's mailbox acked the
        deposit (ack-before-commit). Same generation fence and reset
        bypass as :meth:`put_blob`, but metadata-sized."""
        gen, owner = int(gen), int(host_id)
        if owner in self.lost_hosts():
            raise HostLostError(
                "host %d is fenced — a fenced host must not publish "
                "buddy metadata" % owner)
        row = {"gen": gen, "buddy": int(buddy), "digest": digest,
               "nbytes": int(nbytes)}
        with self._mailbox_lock:
            prev = self._buddy_meta.get(owner)
            if reset:
                self._buddy_meta[owner] = row
                return
            if prev is not None and gen < prev["gen"]:
                raise CoordinationError(
                    "put_buddy_meta generation rewind: host %d is at "
                    "gen %d, refused gen %d" % (owner, prev["gen"],
                                                gen))
            if prev is None or gen > prev["gen"]:
                self._buddy_meta[owner] = row

    def buddy_meta(self, owner):
        """``owner``'s committed metadata row (a copy) or None.
        Read-only and unfenced, same reasoning as :meth:`get_blob`."""
        with self._mailbox_lock:
            rec = self._buddy_meta.get(int(owner))
            return None if rec is None else dict(rec)

    def _evict_orphan_blobs(self):
        """Drop mailboxes whose owner AND recorded buddy are both lost
        (the physical bytes lived in the buddy's RAM — a double
        failure loses them; see transport._PodState)."""
        lost = set(self.lost_hosts())
        with self._blob_lock:
            for o in [o for o, rec in self._blobs.items()
                      if o in lost and rec["buddy"] in lost]:
                del self._blobs[o]
        with self._mailbox_lock:
            for o in [o for o, rec in self._buddy_meta.items()
                      if o in lost and rec["buddy"] in lost]:
                del self._buddy_meta[o]

    def _on_loss(self, newly_lost):
        """Fan out a host-loss: resilience event, mesh re-init, hooks."""
        if not newly_lost:
            return
        self._evict_orphan_blobs()
        live = self.live_hosts()
        record_event("host_lost", hosts=sorted(newly_lost),
                     live=list(live))
        if self._mesh_reinit:
            from ..distributed import mesh as mesh_mod
            mesh_mod.handle_host_loss(sorted(self.lost_hosts()), live)
        for fn in list(self._loss_hooks):
            fn(sorted(newly_lost), live)


# ---------------------------------------------------------------------------
# in-process (threaded) coordinator
# ---------------------------------------------------------------------------

class LocalCoordinator(Coordinator):
    """Thread-based coordinator: N logical hosts in one process.

    This is the tier-1 test vehicle — it runs the exact consensus and
    fencing logic of the pod control plane with no processes, sockets or
    real TPUs, which is how the chaos battery stays fast and
    deterministic."""

    def __init__(self, n_hosts, timeout_s=30.0, detect_loss=True,
                 mesh_reinit=True):
        super(LocalCoordinator, self).__init__(
            n_hosts, timeout_s=timeout_s, detect_loss=detect_loss,
            mesh_reinit=mesh_reinit)
        self._cond = threading.Condition()
        self._lost = {}
        self._joins = {}    # host_id -> nonce (fenced hosts asking back)
        self._rounds = {}   # name -> {"values": {hid: v}, "exits": int}

    def live_hosts(self):
        with self._cond:
            return [i for i in range(self.n_hosts) if i not in self._lost]

    def lost_hosts(self):
        with self._cond:
            return dict(self._lost)

    def mark_lost(self, host_id, reason="declared lost"):
        with self._cond:
            if host_id in self._lost:
                return
            self._lost[host_id] = reason
            self._cond.notify_all()
        self._on_loss([host_id])

    def announce_join(self, host_id, nonce):
        with self._cond:
            if host_id not in self._lost:
                raise CoordinationError(
                    "host %d is not fenced — only a lost host announces "
                    "a rejoin" % host_id)
            self._joins[host_id] = int(nonce)
            self._cond.notify_all()

    def pending_joins(self):
        with self._cond:
            return dict(self._joins)

    def unfence(self, host_id):
        with self._cond:
            self._lost.pop(host_id, None)
            self._joins.pop(host_id, None)
            self._cond.notify_all()

    def resize(self, n_hosts):
        with self._cond:
            open_rounds = [name for name, r in self._rounds.items()
                           if r["result"] is None]
            live = [] if int(n_hosts) >= self.n_hosts else \
                [h for h in range(int(n_hosts), self.n_hosts)
                 if h not in self._lost]
            n = self._check_resize(n_hosts, self.n_hosts, open_rounds,
                                   live)
            if n == self.n_hosts:
                return n
            if n < self.n_hosts:
                for h in range(n, self.n_hosts):
                    self._lost.pop(h, None)
                    self._joins.pop(h, None)
            else:
                for h in range(self.n_hosts, n):
                    self._lost[h] = GROW_FENCE_REASON
            self.n_hosts = n
            self._cond.notify_all()
        record_event("group_resize", n_hosts=n)
        return n

    def all_gather(self, name, host_id, value=None, timeout_s=None):
        deadline = time.monotonic() + (self.timeout_s if timeout_s is None
                                       else float(timeout_s))
        newly_lost = []
        with self._cond:
            if host_id in self._lost:
                raise HostLostError(
                    "host %d is fenced (%s) — rejoin, don't resume"
                    % (host_id, self._lost[host_id]))
            r = self._rounds.setdefault(name, {"values": {}, "exits": 0,
                                               "result": None})
            if host_id in r["values"]:
                raise CoordinationError(
                    "host %d already contributed to round %r — collective "
                    "names must be unique per round" % (host_id, name))
            r["values"][host_id] = value
            self._cond.notify_all()
            while True:
                # completion is STICKY: the first host to see the round
                # complete freezes the result for everyone. Without it,
                # a fast peer can exit, enter the admission path and
                # UN-FENCE the joiner while we are still blocked here —
                # recomputing membership would then add the joiner to
                # waiting_for and wedge this round forever (the joiner
                # is already in the admission round, not this one).
                if r["result"] is not None:
                    break
                waiting_for = [i for i in range(self.n_hosts)
                               if i not in self._lost
                               and i not in r["values"]]
                if not waiting_for:
                    r["result"] = {i: v for i, v in r["values"].items()
                                   if i not in self._lost}
                    self._cond.notify_all()
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if not self.detect_loss:
                        raise BarrierTimeoutError(
                            "round %r timed out waiting for hosts %s"
                            % (name, waiting_for))
                    for i in waiting_for:
                        self._lost[i] = "missed round %r" % name
                        newly_lost.append(i)
                    self._cond.notify_all()
                    continue
                self._cond.wait(remaining)
            # every participant returns the SAME frozen snapshot — the
            # protocol's "identical verdicts on every host" assumption
            # holds even when membership changes mid-exit
            result = dict(r["result"])
            # exit accounting BEFORE the fence check: a host fenced
            # between the freeze and its exit still leaves the round,
            # otherwise the entry (and its gathered payloads) would
            # leak forever — exits could never reach len(result)
            r["exits"] += 1
            if r["exits"] >= len(result):
                self._rounds.pop(name, None)   # last one out cleans up
            if host_id in self._lost:
                # marked lost while blocked in this very round: fence
                raise HostLostError(
                    "host %d is fenced (%s) — rejoin, don't resume"
                    % (host_id, self._lost[host_id]))
        # hooks run OUTSIDE the lock: mesh re-init is arbitrary user code
        self._on_loss(newly_lost)
        return result


# ---------------------------------------------------------------------------
# file-based coordinator (multi-process pods on a shared filesystem)
# ---------------------------------------------------------------------------

class FileCoordinator(Coordinator):
    """Coordinator over a shared directory — one object per PROCESS.

    All state flows through atomically-committed files (io._atomic_write
    discipline: temp file + os.replace), so N processes that share only
    a filesystem agree exactly like LocalCoordinator's threads:

        <root>/lost/host_<i>              tombstone (fence), reason text
        <root>/rounds/<name>/host_<i>.json   one contribution per round
        <root>/hb/hb_<i>.json             heartbeat (liveness lease)

    Polling (``poll_s``) replaces condition variables, backing off
    exponentially up to ``poll_max_s`` so a long barrier does not spin
    the filesystem at 100 Hz per host; round names must
    be unique per live round exactly as with LocalCoordinator
    (PodResilientTrainer namespaces every round by a per-run counter).
    The last host to read a completed round removes its directory, so
    the rounds dir stays bounded over a long job. A RESTARTED process
    must rejoin on a fresh coordinator root as a new participant — its
    old incarnation is fenced, and replaying old round names against a
    stale root would read stale contributions.

    ``hb_deadline_s`` arms heartbeat liveness (SocketCoordinator
    parity): every host touches ``hb/hb_<i>.json`` on each gather poll,
    and any host whose heartbeat file goes stale past the deadline is
    auto-tombstoned by whichever peer notices first — declared-loss-only
    detection stops being a FileCoordinator quirk. A host with NO
    heartbeat file is never auto-fenced (it may not have started; the
    gather deadline still covers it), and the deadline must exceed the
    longest stretch a healthy host computes between gathers (the
    dispatch window), since hosts only heartbeat while polling.
    Staleness compares the scanner's wall clock against the heartbeat
    file's mtime, which on a shared mount is stamped by the WRITER (or
    the NFS server): size ``hb_deadline_s`` to absorb the pod's worst
    cross-host clock skew plus the mount's attribute-cache lag, or
    healthy hosts will be fenced spuriously. (SocketCoordinator has no
    such bound — its ages live on one clock, the server's.)"""

    def __init__(self, root, n_hosts, timeout_s=30.0, poll_s=0.01,
                 detect_loss=True, mesh_reinit=True, poll_max_s=0.25,
                 hb_deadline_s=None):
        super(FileCoordinator, self).__init__(
            n_hosts, timeout_s=timeout_s, detect_loss=detect_loss,
            mesh_reinit=mesh_reinit)
        import os
        self._root = root
        self._lost_dir = os.path.join(root, "lost")
        self._rounds_dir = os.path.join(root, "rounds")
        self._join_dir = os.path.join(root, "joins")
        self._hb_dir = os.path.join(root, "hb")
        self.poll_s = float(poll_s)
        self.poll_max_s = max(self.poll_s, float(poll_max_s))
        self.hb_deadline_s = None if hb_deadline_s is None \
            else float(hb_deadline_s)
        if self.hb_deadline_s is not None:
            # a host only touches its heartbeat between poll sleeps, so
            # the backoff cap must sit well inside the deadline — at or
            # past it, a healthy host mid-sleep looks stale and a peer
            # fences it spuriously
            if self.poll_s * 4.0 > self.hb_deadline_s:
                raise ValueError(
                    "hb_deadline_s=%g is too tight for poll_s=%g: a "
                    "healthy host's heartbeat legitimately ages one "
                    "poll interval between touches" %
                    (self.hb_deadline_s, self.poll_s))
            self.poll_max_s = min(self.poll_max_s,
                                  self.hb_deadline_s / 4.0)
        # per-PROCESS loss knowledge: tombstones written by peers must
        # fire THIS process's _on_loss (mesh re-init is per-process
        # state) exactly once, whoever won the race to write them
        self._known_lost = set()
        self._last_hb_scan = 0.0
        os.makedirs(self._lost_dir, exist_ok=True)
        os.makedirs(self._rounds_dir, exist_ok=True)
        os.makedirs(self._join_dir, exist_ok=True)
        os.makedirs(self._hb_dir, exist_ok=True)

    @staticmethod
    def _safe(name):
        return "".join(c if (c.isalnum() or c in "._-") else "_"
                       for c in name)

    def lost_hosts(self):
        import os
        out = {}
        for f in os.listdir(self._lost_dir):
            if f.startswith("host_"):
                try:
                    with open(os.path.join(self._lost_dir, f)) as fh:
                        out[int(f[5:])] = fh.read().strip()
                except (OSError, ValueError):   # pragma: no cover - race
                    continue
        return out

    def live_hosts(self):
        self._refresh_size()
        lost = self.lost_hosts()
        return [i for i in range(self.n_hosts) if i not in lost]

    def mark_lost(self, host_id, reason="declared lost"):
        import os
        from ..io import _atomic_write
        if host_id in self.lost_hosts():
            return
        _atomic_write(os.path.join(self._lost_dir, "host_%d" % host_id),
                      reason)
        self._known_lost.add(host_id)
        self._on_loss([host_id])

    def announce_join(self, host_id, nonce):
        import os
        from ..io import _atomic_write
        if host_id not in self.lost_hosts():
            raise CoordinationError(
                "host %d is not fenced — only a lost host announces a "
                "rejoin" % host_id)
        _atomic_write(os.path.join(self._join_dir, "host_%d" % host_id),
                      str(int(nonce)))

    def pending_joins(self):
        import os
        out = {}
        for f in os.listdir(self._join_dir):
            if f.startswith("host_"):
                try:
                    with open(os.path.join(self._join_dir, f)) as fh:
                        out[int(f[5:])] = int(fh.read().strip())
                except (OSError, ValueError):  # pragma: no cover - race
                    continue
        return out

    def unfence(self, host_id):
        import os
        for d in (self._lost_dir, self._join_dir):
            try:
                os.unlink(os.path.join(d, "host_%d" % host_id))
            except OSError:   # peer already removed it — idempotent
                pass
        # a future re-loss of this host must re-fire _on_loss here
        self._known_lost.discard(host_id)

    def _refresh_size(self):
        """Adopt a peer's resize: the size record is the one piece of
        FileCoordinator state every process re-reads (poll-time), since
        n_hosts otherwise lives only in each object."""
        import json
        import os
        try:
            with open(os.path.join(self._root, "size.json")) as fh:
                n = int(json.load(fh)["n_hosts"])
        except (OSError, ValueError, KeyError):
            return
        if n != self.n_hosts:
            self.n_hosts = n
            record_event("group_resize", n_hosts=n, adopted=True)

    def resize(self, n_hosts):
        import json
        import os
        from ..io import _atomic_write
        self._refresh_size()
        open_rounds = [
            d for d in os.listdir(self._rounds_dir)
            if os.path.isdir(os.path.join(self._rounds_dir, d))
            and not os.path.exists(os.path.join(self._rounds_dir, d,
                                                "_done.json"))]
        lost = self.lost_hosts()
        live = [] if int(n_hosts) >= self.n_hosts else \
            [h for h in range(int(n_hosts), self.n_hosts)
             if h not in lost]
        n = self._check_resize(n_hosts, self.n_hosts, open_rounds, live)
        if n == self.n_hosts:
            return n
        if n < self.n_hosts:
            for h in range(n, self.n_hosts):
                self.unfence(h)
                try:
                    os.unlink(os.path.join(self._hb_dir,
                                           "hb_%d.json" % h))
                except OSError:
                    pass
        else:
            for h in range(self.n_hosts, n):
                _atomic_write(os.path.join(self._lost_dir,
                                           "host_%d" % h),
                              GROW_FENCE_REASON)
        _atomic_write(os.path.join(self._root, "size.json"),
                      json.dumps({"n_hosts": n}))
        self.n_hosts = n
        record_event("group_resize", n_hosts=n)
        return n

    def _touch_hb(self, host_id):
        """Refresh this host's liveness lease (no-op unless armed)."""
        if self.hb_deadline_s is None:
            return
        import os
        from ..io import _atomic_write
        _atomic_write(os.path.join(self._hb_dir, "hb_%d.json" % host_id),
                      '{"t": %r}' % time.time())

    def _scan_heartbeats(self, lost):
        """Tombstone every un-fenced host whose heartbeat file went
        stale past the deadline; returns the (possibly updated) lost
        map so the caller's poll iteration needs no second lost-dir
        listing. Scans are THROTTLED to ~deadline/4 — stating N
        heartbeat files on every poll tick would be exactly the
        filesystem spin the backoff exists to cool. First tombstone
        wins (atomic-write parity with the gather-timeout path); the
        regular newly-observed machinery fires the loss hooks."""
        if self.hb_deadline_s is None:
            return lost
        import os
        from ..io import _atomic_write
        now = time.time()
        if now - self._last_hb_scan < self.hb_deadline_s / 4.0:
            return lost
        self._last_hb_scan = now
        lost = dict(lost)
        for f in os.listdir(self._hb_dir):
            if not f.startswith("hb_"):
                continue
            try:
                hid = int(f[3:].split(".", 1)[0])
            except ValueError:    # pragma: no cover - foreign file
                continue
            if hid in lost or hid >= self.n_hosts:
                continue
            try:
                age = now - os.stat(os.path.join(self._hb_dir,
                                                 f)).st_mtime
            except OSError:       # pragma: no cover - peer mid-replace
                continue
            if age > self.hb_deadline_s:
                reason = ("missed heartbeat (%.2fs > %.2fs)"
                          % (age, self.hb_deadline_s))
                _atomic_write(
                    os.path.join(self._lost_dir, "host_%d" % hid),
                    reason)
                lost[hid] = reason
        return lost

    def all_gather(self, name, host_id, value=None, timeout_s=None):
        import json
        import os
        from ..io import _atomic_write
        self._refresh_size()
        deadline = time.monotonic() + (self.timeout_s if timeout_s is None
                                       else float(timeout_s))
        rd = os.path.join(self._rounds_dir, self._safe(name))
        os.makedirs(rd, exist_ok=True)
        lost = self.lost_hosts()
        if host_id in lost:
            raise HostLostError(
                "host %d is fenced (%s) — rejoin, don't resume"
                % (host_id, lost[host_id]))
        mine = os.path.join(rd, "host_%d.json" % host_id)
        if os.path.exists(mine):
            # same split-brain guard as LocalCoordinator: never let an
            # imposter (or a replayed round name) overwrite a live value
            raise CoordinationError(
                "host %d already contributed to round %r — collective "
                "names must be unique per round" % (host_id, name))
        _atomic_write(mine, json.dumps({"value": value}))
        done_path = os.path.join(rd, "_done.json")
        self._touch_hb(host_id)
        sleep_s = self.poll_s
        while True:
            # completion is STICKY (LocalCoordinator parity): the first
            # process to see every live host present freezes the member
            # snapshot in _done.json. Without it, a fast peer can exit
            # and un-fence a rejoining host while we are still polling
            # — recomputing membership would add the joiner to
            # waiting_for and wedge this round forever.
            if os.path.exists(done_path):
                try:
                    with open(done_path) as fh:
                        members = json.load(fh)
                    break
                except (OSError, ValueError):  # pragma: no cover - race
                    pass    # mid-replace glimpse: poll again
            self._touch_hb(host_id)
            lost = self._scan_heartbeats(self.lost_hosts())
            if host_id in lost:
                # fenced while polling: stop competing NOW. Also load-
                # bearing for cleanup: the frozen member set excludes
                # us, so once every member acks, the round dir is
                # removed under our feet — without this check the
                # listdir below would crash instead of fencing
                raise HostLostError(
                    "host %d is fenced (%s) — rejoin, don't resume"
                    % (host_id, lost[host_id]))
            try:
                present = {int(f[5:-5]) for f in os.listdir(rd)
                           if f.startswith("host_")
                           and f.endswith(".json")}
            except OSError:
                # the members finished and removed the round dir in the
                # window since the fence check — the next iteration's
                # check raises the HostLostError (deadline-bounded so a
                # filesystem anomaly can never spin forever)
                if time.monotonic() >= deadline:
                    raise BarrierTimeoutError(
                        "round %r directory vanished and host %d was "
                        "never fenced" % (name, host_id))
                time.sleep(self.poll_s)
                continue
            waiting_for = [i for i in range(self.n_hosts)
                           if i not in lost and i not in present]
            if not waiting_for:
                # claim the freeze atomically: hard-link of a complete
                # temp file, so the FIRST freezer wins outright and no
                # reader ever sees a partial or second snapshot (two
                # hosts with divergent lost views must not freeze
                # different member sets). Loop back to read the
                # canonical file — even the winner re-reads it.
                import tempfile
                fd, tmp = tempfile.mkstemp(dir=rd, suffix=".tmp")
                try:
                    with os.fdopen(fd, "w") as fh:
                        fh.write(json.dumps(sorted(present - set(lost))))
                    try:
                        os.link(tmp, done_path)
                    except OSError:     # a peer froze first — use theirs
                        pass
                finally:
                    os.unlink(tmp)
                continue
            if time.monotonic() >= deadline:
                if not self.detect_loss:
                    raise BarrierTimeoutError(
                        "round %r timed out waiting for hosts %s"
                        % (name, waiting_for))
                for i in waiting_for:
                    # first tombstone wins; duplicates are idempotent —
                    # _on_loss firing is keyed on _known_lost below, so
                    # losing this race still re-inits OUR mesh
                    if i not in self.lost_hosts():
                        _atomic_write(
                            os.path.join(self._lost_dir, "host_%d" % i),
                            "missed round %r" % name)
                continue
            # exponential backoff from poll_s up to poll_max_s (clamped
            # to the remaining deadline): a long barrier idles at a few
            # Hz instead of hammering the filesystem at 1/poll_s
            time.sleep(min(sleep_s,
                           max(0.0, deadline - time.monotonic())))
            sleep_s = min(sleep_s * 2.0, self.poll_max_s)
        lost = self.lost_hosts()
        if host_id in lost:
            raise HostLostError(
                "host %d is fenced (%s) — rejoin, don't resume"
                % (host_id, lost[host_id]))
        result = {}
        for i in members:
            with open(os.path.join(rd, "host_%d.json" % i)) as fh:
                result[i] = json.load(fh)["value"]
        # last one out cleans up (LocalCoordinator parity): every value
        # is written before any ack, and removal needs every reader's
        # ack — so nobody can lose a file they still need. Lost hosts
        # never ack; their rounds leak, bounded by the loss count.
        _atomic_write(os.path.join(rd, "ack_%d" % host_id), "")
        try:
            acked = {int(f[4:]) for f in os.listdir(rd)
                     if f.startswith("ack_")}
            if acked >= set(result):
                import shutil
                shutil.rmtree(rd, ignore_errors=True)
        except (OSError, ValueError):   # pragma: no cover - lost race
            pass
        # fire for every loss THIS process has not yet reacted to —
        # including tombstones another process won the race to write:
        # mesh re-init is per-process state, so a survivor that merely
        # OBSERVES a loss must still rebuild its collectives. Grown
        # slots are born fenced but were never members: no hooks, and
        # they stay OUT of _known_lost so a real loss after they join
        # still fires (LocalCoordinator.resize parity).
        growing = {h for h, r in lost.items()
                   if str(r).startswith(GROW_FENCE_REASON)}
        newly_observed = sorted(set(lost) - growing - self._known_lost)
        self._known_lost.update(h for h in lost if h not in growing)
        self._on_loss(newly_observed)
        return result


# ---------------------------------------------------------------------------
# socket-backed coordinator (multi-process pods WITHOUT shared storage)
# ---------------------------------------------------------------------------

class SocketCoordinator(Coordinator):
    """Coordinator over a TCP rendezvous service — one object per
    PROCESS, no shared filesystem anywhere.

    The full protocol of Local/FileCoordinator (sticky round
    completion, tombstone fencing, join announcements, consensus
    elections) lives server-side in :class:`~.transport.CoordServer`
    (in-process for tests, standalone via ``tools/coordsvc.py``); this
    client implements the :class:`Coordinator` contract over it, so
    :class:`PodResilientTrainer`/:class:`ElasticTrainer` run unmodified.

    What the network transport adds over FileCoordinator:

      * **Real liveness.** A daemon thread heartbeats the server every
        ``hb_interval_s``; the server tombstones any host whose
        heartbeat goes stale past its ``hb_deadline_s`` — a
        ``kill -9`` is detected by the DEADLINE, not by a peer calling
        :meth:`mark_lost` or waiting out a gather timeout. Every
        response carries the server's lost map, so survivors fire their
        loss hooks (mesh re-init) even with no gather in flight.
      * **Transient-fault tolerance.** Socket errors reconnect and
        re-send through the shared :class:`~.resilience.RetryPolicy`;
        round contributions are idempotent server-side (keyed by
        ``(name, host_id)`` plus a per-call token), so a replay after a
        broken pipe never double-counts — and an imposter with a
        different token still gets the split-brain
        :class:`CoordinationError`.
      * **Observability.** ``transport_reconnects_total`` and the
        per-host ``transport_heartbeat_lag`` gauge ride
        ``resilience.metrics()``.

    ``host_id`` binds the object to its host (the heartbeat identity);
    the per-call ``host_id`` arguments of the contract remain and must
    match in a real deployment. ``heartbeat=False`` builds a passive
    client (observers, tests driving liveness by hand).

    ``address`` may be a LIST of endpoints (``"h:p1,h:p2"`` or a list)
    — a term-replicated CoordServer group in index order. Failover is
    transparent: on primary loss the client rotates to the promoted
    standby inside its retry budget, contributions replay idempotently
    by ``(name, host_id, token)``, and a stale ex-primary's responses
    are refused by term (``transport_stale_primary``) — the trainers
    above this class run UNMODIFIED through a coordinator SIGKILL."""

    def __init__(self, address, n_hosts, host_id, timeout_s=30.0,
                 poll_s=0.01, poll_max_s=0.25, detect_loss=True,
                 mesh_reinit=True, heartbeat=True, hb_interval_s=0.5,
                 retry_policy=None, mailbox=True,
                 mailbox_host="127.0.0.1", mailbox_port=0):
        super(SocketCoordinator, self).__init__(
            n_hosts, timeout_s=timeout_s, detect_loss=detect_loss,
            mesh_reinit=mesh_reinit)
        from .transport import CoordClient
        self.host_id = int(host_id)
        self._mb_server = None
        self._mb_addrs = {}
        self.poll_s = float(poll_s)
        self.poll_max_s = max(self.poll_s, float(poll_max_s))
        self._known_lost = set()
        self._known_lock = threading.Lock()
        self._lost_seen_v = -1
        self._token_seq = 0
        # per-INCARNATION token base: a reconnect replay from this
        # process matches its own token (idempotent), while a duplicate
        # process launched with the same host_id generates a different
        # base and still gets the split-brain CoordinationError
        import os as _os
        import random as _random
        self._token_base = "%d.%08x" % (_os.getpid(),
                                        _random.getrandbits(32))
        self._client = CoordClient(address, host_id=self.host_id,
                                   retry_policy=retry_policy)
        # hello validates the pod size before anything else rides the
        # connection; the heartbeat (when armed) then takes the lease
        with obs.span("coord.hello", host=self.host_id):
            self._call("hello", n_hosts=self.n_hosts)
        if mailbox:
            # p2p buddy mailbox endpoint: started and registered BEFORE
            # this constructor returns (and so before any pod_start
            # barrier completes), so every peer can resolve this host's
            # address by the time the first gen-0 seed streams.
            from . import buddy as buddy_mod
            from .transport import MailboxServer
            self._mb_server = MailboxServer(
                buddy_mod.BuddyMailbox(host_id=self.host_id),
                host=mailbox_host, port=int(mailbox_port))
            self._call("mailbox_hello", addr=self._mb_server.address)
        if obs.enabled():
            # align this process's span timestamps to the coordination
            # server's clock (min-RTT midpoint probe) — what lets one
            # merged timeline order spans across hosts. Best-effort:
            # an old server without the `time` op changes nothing.
            try:
                obs.probe_clock_offset(lambda cmd: self._call(cmd))
            except Exception:
                pass
        if heartbeat:
            self._client.start_heartbeat(interval_s=hb_interval_s,
                                         on_lost=self._observe_lost)
        else:
            self._client._lost_cb = self._observe_lost

    # -- loss observation (runs on gather AND heartbeat threads) ----------
    def _observe_lost(self, lost, version=None):
        """Fire _on_loss exactly once per process per tombstone —
        including ones the server's heartbeat monitor wrote. The update
        of _known_lost happens BEFORE the hooks so the nested
        live_hosts() calls inside _on_loss cannot re-enter. ``version``
        is the server's lost_v: a delayed delivery older than one we
        already processed is DROPPED, so a pre-unfence map can never
        re-fire hooks for a host this coordinator just readmitted (or
        poison _known_lost into suppressing its next real loss)."""
        with self._known_lock:
            if version is not None:
                if version < self._lost_seen_v:
                    return
                self._lost_seen_v = version
            # a GROWN slot's birth fence is not a loss: the host was
            # never a member, so no hooks fire and it stays out of
            # _known_lost (else its first REAL loss after joining
            # would be suppressed) — LocalCoordinator.resize parity
            growing = {h for h, r in lost.items()
                       if str(r).startswith(GROW_FENCE_REASON)} \
                if isinstance(lost, dict) else set()
            newly = sorted(set(lost) - growing - self._known_lost
                           - {self.host_id})
            self._known_lost.update(h for h in lost
                                    if h not in growing)
        if newly:
            self._on_loss(newly)

    def _call(self, cmd, **fields):
        """call() with server errors mapped onto the Coordinator error
        taxonomy (transport errors — ConnectionError — raise through
        as transients for the caller's policy)."""
        try:
            return self._client.call(cmd, **fields)
        except CoordinationError:
            raise
        except RuntimeError as e:
            raise CoordinationError(str(e))

    # -- contract ----------------------------------------------------------
    def lost_hosts(self):
        self._call("lost")            # call() refreshed last_lost
        return dict(self._client.last_lost)

    def live_hosts(self):
        lost = self.lost_hosts()
        return [i for i in range(self.n_hosts) if i not in lost]

    def mark_lost(self, host_id, reason="declared lost"):
        # the response's lost map runs through _observe_lost, which
        # fires _on_loss for the newly tombstoned host
        self._call("mark_lost", host=int(host_id), reason=reason)

    def announce_join(self, host_id, nonce):
        self._call("announce_join", host=int(host_id), nonce=int(nonce))

    def pending_joins(self):
        joins = self._call("pending_joins").get("joins", {})
        return {int(h): int(n) for h, n in joins.items()}

    # -- member registry (serving fleet) -----------------------------------
    def put_info(self, info):
        """Publish this host's JSON blob to the server's member
        registry (last write wins). The serving fleet advertises each
        replica's HTTP address + artifact generation here so the
        router needs no static fleet configuration."""
        self._call("put_info", info=info)

    # -- buddy-snapshot mailboxes (server-side store) ----------------------
    def put_blob(self, host_id, gen, buddy, blob, reset=False):
        """Mailbox write on the CoordServer (see Coordinator.put_blob):
        synchronously replicated to standbys and snapshot-covered, so
        an acked snapshot survives coordinator failover. The server's
        ``blob_max_bytes`` refusal surfaces as
        :class:`BlobTooLargeError`."""
        try:
            resp = self._call("put_blob", host=int(host_id),
                              gen=int(gen), buddy=int(buddy),
                              blob=blob, reset=bool(reset))
        except CoordinationError as e:
            if "blob_max_bytes" in str(e):
                raise BlobTooLargeError(str(e))
            raise
        if "fenced" in resp:
            raise HostLostError(
                "host %d is fenced (%s) — a fenced host must not "
                "publish buddy snapshots" % (int(host_id),
                                             resp["fenced"]))

    def get_blob(self, owner, meta_only=False):
        resp = self._call("get_blob", owner=int(owner),
                          meta_only=bool(meta_only))
        if resp.get("miss"):
            return None
        out = {"gen": int(resp["gen"]), "buddy": int(resp["buddy"])}
        if not meta_only:
            out["blob"] = resp.get("blob")
        return out

    # -- p2p buddy mailboxes (real per-host endpoints) ---------------------
    def mailbox_of(self, host_id):
        """This host's own mailbox when the endpoint is armed; the
        in-process base registry otherwise (mailbox=False clients,
        observers)."""
        if self._mb_server is not None \
                and int(host_id) == self.host_id:
            return self._mb_server.mailbox
        return super(SocketCoordinator, self).mailbox_of(host_id)

    def _mailbox_addr(self, host_id):
        """Resolve a peer's MailboxServer address from the local cache,
        refreshed from the coordinator's replicated address book on a
        miss."""
        h = int(host_id)
        addr = self._mb_addrs.get(h)
        if addr is None:
            resp = self._call("buddy_meta")
            self._mb_addrs.update(
                {int(k): a
                 for k, a in resp.get("addrs", {}).items()})
            addr = self._mb_addrs.get(h)
        return addr

    def _mailbox_request(self, host_id, req):
        """One-shot request against ``host_id``'s mailbox endpoint. A
        dead/renumbered endpoint drops the cached address before the
        ConnectionError propagates, so the next attempt re-resolves."""
        from .transport import mailbox_request
        h = int(host_id)
        addr = self._mailbox_addr(h)
        if addr is None:
            raise ConnectionError(
                "no mailbox endpoint registered for host %d" % h)
        try:
            return mailbox_request(addr, req)
        except ConnectionError:
            self._mb_addrs.pop(h, None)
            raise

    def mailbox_send(self, owner, at, payload):
        at = int(at)
        if self._mb_server is not None and at == self.host_id:
            return self._mb_server.mailbox.deposit(owner, payload)
        if self._mb_server is None:
            return super(SocketCoordinator, self).mailbox_send(
                owner, at, payload)
        resp = self._mailbox_request(
            at, {"cmd": "mb_deposit", "owner": int(owner),
                 "payload": payload})
        if "error" in resp:
            raise ConnectionError(
                "mailbox deposit for host %d failed: %s"
                % (int(owner), resp["error"]))
        return resp

    def mailbox_fetch(self, owner, at):
        at = int(at)
        if self._mb_server is not None and at == self.host_id:
            try:
                return self._mb_server.mailbox.reconstruct(owner)
            except LookupError:
                return None
        if self._mb_server is None:
            return super(SocketCoordinator, self).mailbox_fetch(
                owner, at)
        resp = self._mailbox_request(
            at, {"cmd": "mb_fetch", "owner": int(owner)})
        if resp.get("miss"):
            return None
        if "refused" in resp or "error" in resp:
            raise RuntimeError(
                "mailbox fetch for host %d refused: %s"
                % (int(owner),
                   resp.get("refused") or resp.get("error")))
        return resp

    def put_buddy_meta(self, host_id, gen, buddy, digest, nbytes,
                       reset=False):
        """Metadata commit on the CoordServer (replicated + snapshot-
        covered) — see Coordinator.put_buddy_meta."""
        resp = self._call("put_buddy_meta", host=int(host_id),
                          gen=int(gen), buddy=int(buddy),
                          digest=digest, nbytes=int(nbytes),
                          reset=bool(reset))
        if "fenced" in resp:
            raise HostLostError(
                "host %d is fenced (%s) — a fenced host must not "
                "publish buddy metadata" % (int(host_id),
                                            resp["fenced"]))

    def buddy_meta(self, owner):
        resp = self._call("buddy_meta", owner=int(owner))
        if resp.get("miss"):
            return None
        out = {"gen": int(resp["gen"]), "buddy": int(resp["buddy"]),
               "digest": resp.get("digest"),
               "nbytes": int(resp.get("nbytes", 0))}
        if resp.get("addr"):
            # piggybacked address of the recorded buddy's endpoint —
            # prime the cache so the restore-time pull needs no extra
            # round-trip
            self._mb_addrs[out["buddy"]] = resp["addr"]
        return out

    def members(self):
        """One snapshot of the whole membership picture:
        ``{"n_hosts", "hb_deadline_s", "hb_age": {host: seconds},
        "info": {host: blob}, "lost": {host: reason}}`` — host keys as
        ints. The routing table is derived from exactly this (live =
        registered, not fenced), and ``hb_deadline_s`` lets a client
        judge a lease live-looking by the same bound the server's
        monitor fences by."""
        resp = self._call("members")
        n = resp.get("n_hosts")
        if n is not None and int(n) != self.n_hosts:
            # a peer resized the group (dynamic resize): adopt — the
            # server is the size's single source of truth, and a stale
            # client-side n_hosts would mis-enumerate live_hosts()
            self.n_hosts = int(n)
            record_event("group_resize", n_hosts=self.n_hosts,
                         adopted=True)
        return {"n_hosts": resp.get("n_hosts"),
                "resize_v": resp.get("resize_v"),
                "hb_deadline_s": resp.get("hb_deadline_s"),
                "hb_age": {int(h): float(v)
                           for h, v in resp.get("hb_age", {}).items()},
                "info": {int(h): v
                         for h, v in resp.get("info", {}).items()},
                "lost": {int(h): v
                         for h, v in resp.get("lost", {}).items()}}

    def unfence(self, host_id):
        self._call("unfence", host=int(host_id))
        with self._known_lock:
            # a future re-loss of this host must re-fire _on_loss here
            self._known_lost.discard(int(host_id))

    def resize(self, n_hosts):
        """Server-side dynamic resize (primary-replicated, snapshot-
        covered); adopts the new size locally. Raises
        CoordinationError mid-round or for a live id in a shrink range
        (the server's named refusals)."""
        if int(n_hosts) < 1:
            # local pre-check so the caller-facing contract matches
            # Local/File: ValueError for a bad ARGUMENT, reserving
            # CoordinationError for the protocol's named refusals
            raise ValueError("resize: n_hosts must be >= 1, got %d"
                             % int(n_hosts))
        resp = self._call("resize", n_hosts=int(n_hosts))
        self.n_hosts = int(resp.get("n_hosts", n_hosts))
        record_event("group_resize", n_hosts=self.n_hosts)
        return self.n_hosts

    def all_gather(self, name, host_id, value=None, timeout_s=None):
        # the span covers put + poll-to-freeze + ack: the whole
        # barrier WAIT, which is exactly what makes an elastic window
        # barrier attributable (compute vs coordination) on a merged
        # timeline
        with obs.span("coord.gather", round=name, host=host_id):
            return self._all_gather_traced(name, host_id, value,
                                           timeout_s)

    def _all_gather_traced(self, name, host_id, value, timeout_s):
        deadline = time.monotonic() + (self.timeout_s if timeout_s is None
                                       else float(timeout_s))
        with self._known_lock:
            self._token_seq += 1
            token = "h%d.%s.%d" % (self.host_id, self._token_base,
                                   self._token_seq)
        resp = self._call("put", name=name, host=host_id, value=value,
                          token=token)
        if "fenced" in resp:
            raise HostLostError(
                "host %d is fenced (%s) — rejoin, don't resume"
                % (host_id, resp["fenced"]))
        sleep_s = self.poll_s
        while True:
            try:
                resp = self._call("poll", name=name, host=host_id)
            except CoordinationError as e:
                if "unknown" not in str(e):
                    raise
                # "round unknown" AFTER our put landed: the service
                # failed over to a standby the contribution had not
                # replicated to yet (a sub-sync-window race). The put
                # is idempotent keyed by (name, host, token) — re-send
                # it against the promoted member and keep polling; a
                # replay the new primary DID inherit answers "resent"
                resp = self._call("put", name=name, host=host_id,
                                  value=value, token=token)
                if "fenced" in resp:
                    raise HostLostError(
                        "host %d is fenced (%s) — rejoin, don't resume"
                        % (host_id, resp["fenced"]))
                continue
            if "fenced" in resp:
                raise HostLostError(
                    "host %d is fenced (%s) — rejoin, don't resume"
                    % (host_id, resp["fenced"]))
            if "done" in resp:
                break
            if time.monotonic() >= deadline:
                waiting = resp.get("waiting", [])
                if not self.detect_loss:
                    raise BarrierTimeoutError(
                        "round %r timed out waiting for hosts %s"
                        % (name, waiting))
                for i in waiting:
                    # client-driven fencing at the gather deadline —
                    # the slow path; the server's heartbeat monitor
                    # usually tombstones a dead host long before this
                    self._call("mark_lost", host=i,
                               reason="missed round %r" % name)
                continue
            time.sleep(min(sleep_s,
                           max(0.0, deadline - time.monotonic())))
            sleep_s = min(sleep_s * 2.0, self.poll_max_s)
        result = {int(h): v for h, v in resp["values"].items()}
        if host_id in self._client.last_lost:
            # fenced between the freeze and our exit (File/Local
            # parity): the snapshot exists for the survivors; we fence
            raise HostLostError(
                "host %d is fenced (%s) — rejoin, don't resume"
                % (host_id, self._client.last_lost[host_id]))
        # last one out cleans up server-side; fenced hosts never ack —
        # their rounds leak server-side, bounded by the loss count
        self._call("ack", name=name, host=host_id)
        return result

    def close(self):
        if self._mb_server is not None:
            self._mb_server.close()
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# pod-level resilient training
# ---------------------------------------------------------------------------

class PodResilientTrainer(object):
    """Coordinated auto-recovery across an N-host pod.

    Wraps N per-host :class:`~.resilience.ResilientTrainer` s — each
    with its own executor, Scope and checkpoint dir. In production every
    host process builds exactly one trainer and they meet on a
    :class:`FileCoordinator`; in tests all N live in one process on a
    :class:`LocalCoordinator` (threads), which exercises the identical
    consensus protocol.

    Protocol, per dispatch window:

      1. every host dispatches its window and (at a checkpoint boundary)
         saves its shards;
      2. status exchange (all_gather): ok / transient / fatal;
      3. all ok -> commit and continue. Any fatal -> the whole pod
         aborts (a shape bug replays identically — retrying burns the
         budget on every host). Any transient -> pod-wide recovery:
         every host scrubs its checkpoint dir WITHOUT loading payloads
         (io.scrub_checkpoint), the coordinator elects the max step
         validated on every live host, and every host restores exactly
         that step (io.load_checkpoint(step=...): no silent fallback —
         a mismatched restore would deadlock the collectives).

    Because each host's checkpoint carries params, optimizer moments AND
    the PRNG step counter, the replayed pod trajectory is bitwise
    identical to a fault-free run. The restart budget is SHARED: rewinds
    are pod-wide, so every host's counter advances in lockstep and the
    pod gives up together with RestartBudgetExceededError.
    """

    def __init__(self, trainers, coordinator=None, max_restarts=3,
                 host_id=None, buddy=True, buddy_compress="zlib",
                 buddy_p2p=True, buddy_delta=True,
                 buddy_rebase_every=8):
        """``host_id=None`` (simulation): ``trainers`` holds ALL N hosts
        and run() drives them on N threads. ``host_id=i`` (production,
        one process per host): ``trainers`` holds exactly THIS host's
        trainer, ``coordinator`` is the shared rendezvous (e.g. a
        FileCoordinator over a common root with ``n_hosts`` = pod size),
        and run() drives the single host loop in the calling thread —
        its peers are other processes, not threads.

        ``buddy=True`` (default) arms the in-memory buddy-checkpoint
        tier (:mod:`framework.buddy`): every committed window boundary
        each host mails a compressed scope snapshot to its ring buddy
        through the coordination plane, and a recovery round first
        tries the agreed buddy restore (≤ 1 window lost, no disk read)
        before the consensus disk rewind. ``buddy_compress`` picks the
        snapshot codec: "zlib" (default) is bitwise-lossless — the
        restore stays bitwise the uninterrupted reference; "q8" is the
        lossy block codec for operators who accept its error envelope;
        None mails full-width bytes.

        ``buddy_p2p=True`` (default) keeps snapshot PAYLOADS in peer
        mailboxes (the owner's own plus its ring buddy's) with the
        coordinator holding only the metadata table;
        ``buddy_p2p=False`` is the legacy coordinator-mailbox mode
        (payloads ride put_blob, bounded by the coordinator's
        blob_max_bytes ceiling). ``buddy_delta=True`` ships only the
        leaves whose digest changed since the last acked generation,
        re-based to a full send every ``buddy_rebase_every`` windows;
        deltas require a bitwise codec, so q8 always sends full."""
        if not trainers:
            raise ValueError("PodResilientTrainer needs >= 1 trainer")
        if buddy_compress not in (None, "zlib", "q8"):
            raise ValueError("buddy_compress must be None, 'zlib' or "
                             "'q8', got %r" % (buddy_compress,))
        if int(buddy_rebase_every) < 1:
            raise ValueError("buddy_rebase_every must be >= 1, got %r"
                             % (buddy_rebase_every,))
        self._buddy = bool(buddy)
        self._buddy_compress = buddy_compress
        self._buddy_p2p = bool(buddy_p2p)
        self._buddy_delta = bool(buddy_delta)
        self._buddy_rebase_every = int(buddy_rebase_every)
        # per-host sender-side delta trackers (simulation mode runs
        # every host in this one object, so a dict keyed by host id)
        self._buddy_trackers = {}
        self._trainers = list(trainers)
        every = {t._checkpoint_every for t in self._trainers}
        window = {t._steps_per_dispatch for t in self._trainers}
        keep = {t._keep_last for t in self._trainers}
        if len(every) != 1 or len(window) != 1 or len(keep) != 1:
            # the recovery protocol assumes identical control flow on
            # every host: same windows, same checkpoint boundaries,
            # same pruning horizon
            raise ValueError(
                "all pod trainers must agree on checkpoint_every, "
                "steps_per_dispatch and keep_last (got %s / %s / %s)"
                % (sorted(every), sorted(window), sorted(keep)))
        if min(keep) < 2:
            # a host that faulted BEFORE the window's save holds one
            # fewer checkpoint than its ok peers; keep_last=1 would let
            # the peers prune the last step everyone shares, turning a
            # recoverable transient into a NoQuorumError cold start
            raise ValueError(
                "pod trainers need keep_last >= 2: the consensus "
                "election requires the previous common checkpoint to "
                "survive the ok hosts' pruning")
        if len({t._feed is not None for t in self._trainers}) != 1:
            # feed-driven and list-driven hosts cannot mix: the window
            # protocol (cursor exchange, drain consensus) must be
            # uniform across the pod
            raise ValueError(
                "either every pod trainer has a ShardedFeed attached "
                "(feed=) or none does")
        self._coordinator = coordinator or LocalCoordinator(
            len(self._trainers))
        self._host_id = None if host_id is None else int(host_id)
        if self._host_id is None:
            if self._coordinator.n_hosts != len(self._trainers):
                raise ValueError(
                    "coordinator expects %d hosts but %d trainers were "
                    "given" % (self._coordinator.n_hosts,
                               len(self._trainers)))
        else:
            if len(self._trainers) != 1:
                raise ValueError(
                    "host_id mode is one-process-per-host: pass exactly "
                    "this host's trainer (got %d)" % len(self._trainers))
            if not 0 <= self._host_id < self._coordinator.n_hosts:
                raise ValueError(
                    "host_id %d out of range for a %d-host coordinator"
                    % (self._host_id, self._coordinator.n_hosts))
        self._max_restarts = int(max_restarts)
        # feed topology must match the pod, and each feed must sit in
        # its trainer's host slot: a copy-pasted host_id would silently
        # train one host's lanes N times and never read the rest
        for i, t in enumerate(self._trainers):
            if t._feed is None:
                continue
            want_hid = i if self._host_id is None else self._host_id
            if t._feed.n_hosts != self._coordinator.n_hosts:
                raise ValueError(
                    "trainer %d's ShardedFeed was built for %d hosts "
                    "but the pod has %d — lane partitioning would not "
                    "cover the dataset" % (i, t._feed.n_hosts,
                                           self._coordinator.n_hosts))
            if t._feed._host_id != want_hid:
                raise ValueError(
                    "trainer %d's ShardedFeed carries host_id %d but "
                    "occupies host slot %d — every host would read the "
                    "wrong lanes" % (i, t._feed._host_id, want_hid))
        # advances once per run() on EVERY host (runs are lockstep like
        # everything else), namespacing round names so a second run()
        # on the same coordinator never collides with the first's rounds
        self._run_seq = 0

    @property
    def coordinator(self):
        return self._coordinator

    def _agree_poison(self, co, hid, run_tag, rnd, trainer, step, err):
        """Pod-wide poison-batch agreement — one extra gather in the
        recovery round. The host whose numeric policy localized a
        :class:`~.resilience.NumericFaultError` publishes the bad
        batch's global index; EVERY host adds the agreed union to its
        trainer's poison set, so the post-restore replay skips the
        batch pod-wide and the recovered trajectory stays lockstep
        (bitwise equal to an uninterrupted run without that batch).
        Hosts with nothing to report still join the gather — recovery
        rounds are lockstep like everything else."""
        from . import resilience
        mine = []
        if isinstance(err, resilience.NumericFaultError) \
                and not isinstance(err,
                                   resilience.SkipBudgetExceededError):
            b = err.batch_index
            if b is None:
                b = step + int(err.window_offset or 0)
            mine = [int(b)]
        shared = co.all_gather("%sp%d" % (run_tag, rnd), hid, mine)
        agreed = sorted({int(b) for v in shared.values()
                         for b in (v or [])})
        culprit = getattr(err, "culprit", None)
        for b in agreed:
            if b not in trainer._poison_batches:
                trainer._poison_batches.add(b)
                record_event("poison_batch", batch=b, step=step,
                             **({} if culprit is None
                                else {"culprit": culprit}))
        return agreed

    @staticmethod
    def _scope_of(trainer):
        from .scope import global_scope
        return trainer._scope if trainer._scope is not None \
            else global_scope()

    def _buddy_send(self, co, hid, trainer, members, gen, feed,
                    reset=False):
        """Mail this window boundary's snapshot to the ring buddy —
        best-effort by construction (:func:`buddy.send_snapshot`
        swallows every failure into a ``buddy_send_fail`` event), so
        the training loop's control flow never depends on it."""
        if not self._buddy:
            return
        from . import buddy as buddy_mod
        tracker = None
        if self._buddy_p2p and self._buddy_delta:
            tracker = self._buddy_trackers.get(int(hid))
            if tracker is None:
                tracker = self._buddy_trackers[int(hid)] = \
                    buddy_mod.DeltaTracker(
                        rebase_every=self._buddy_rebase_every)
        buddy_mod.send_snapshot(co, hid, members, gen,
                                self._scope_of(trainer),
                                compress=self._buddy_compress,
                                feed=feed, reset=reset,
                                p2p=self._buddy_p2p, tracker=tracker)

    def _buddy_restore(self, co, hid, run_tag, rnd, trainer, gen, live,
                       lost=(), shardings=None, feed=None,
                       feed_lags=None, agreed=False, reason=None):
        """Pod-agreed buddy restore at generation ``gen``: the warm
        path every recovery round tries before the consensus disk
        rewind. Returns the restored step (== ``gen``) on success or
        None for the disk fallback — the typed reason
        (:data:`buddy.FALLBACK_REASONS`) is recorded on the
        ``buddy_restore`` event either way. ``agreed=True`` means the
        caller already ran :func:`buddy.agree_plan` this round
        (ElasticTrainer does, BEFORE the budget block — a
        ``buddy_and_host_lost`` verdict demotes the free pp rewind)
        and passes its ``reason``."""
        if not self._buddy:
            return None
        from . import buddy as buddy_mod
        name = "%sb%d" % (run_tag, rnd)
        live, lost = sorted(live), sorted(lost)
        if not agreed:
            reason = buddy_mod.agree_plan(
                co, hid, name, live, lost,
                sorted(set(live) | set(lost)), gen,
                p2p=self._buddy_p2p)
        if reason is None:
            ok, feed_state = buddy_mod.restore_agreed(
                co, hid, name, gen, self._scope_of(trainer),
                shardings=shardings,
                need_feed_state=feed is not None,
                p2p=self._buddy_p2p)
            if ok:
                if feed is not None:
                    feed.restore(feed_state, lags=feed_lags)
                # the buddy election IS this round's restore
                # consensus: record it in the same shape as
                # elect_restore_step so the recovery contract
                # (consensus + pod_restore events) holds unchanged
                record_event("consensus", step=int(gen),
                             hosts=len(live), quorum=len(live))
                record_event("buddy_restore", outcome="ok",
                             step=int(gen))
                return int(gen)
            reason = "snapshot_torn"
        record_event("buddy_restore", outcome=reason, step=int(gen))
        return None

    def run(self, feeds, fetch_list=None, steps=None):
        """Run the pod to completion, recovering from transient faults.

        ``feeds``: either ONE list of per-step feed dicts (replicated to
        every host — the data-parallel-replica shape) or a list of N
        per-host feed lists of EQUAL length (each host trains its own
        stream). Returns the per-host fetch lists ``[n_hosts][n_steps]``.

        ``feeds=None`` switches to the elastic data plane: every
        trainer's attached :class:`~..reader.ShardedFeed` supplies its
        windows (``steps`` bounds the committed BATCHES per host, in
        dispatch-window increments; the run ends early
        once every live host's feed drains), the window exchange carries
        each host's cursor, and checkpoints persist the agreed pod-wide
        cursor map so a rewind replays the exact batch sequence. Each
        host's result is its flat list of committed per-batch fetches.

        In ``host_id`` mode feeds is THIS host's list of per-step feed
        dicts and the return value is its fetch list ``[n_steps]`` —
        the peers run the same call in their own processes.
        """
        from . import resilience
        if feeds is None:
            if self._trainers[0]._feed is None:
                raise ValueError(
                    "run(feeds=None) pulls from ShardedFeeds — attach "
                    "one to every trainer (feed=) or pass feeds")
            if steps is None or int(steps) < 1:
                raise ValueError(
                    "feed-driven pod runs need steps= >= 1 (a lockstep "
                    "window bound; draining feeds end the run early)")
        if self._host_id is not None:
            self._run_seq += 1
            with resilience.context(host=self._host_id):
                return self._host_loop(self._host_id,
                                       "r%d." % self._run_seq,
                                       None if feeds is None
                                       else list(feeds),
                                       fetch_list, steps=steps)
        n_hosts = len(self._trainers)
        if feeds is None:
            per_host = [None] * n_hosts
        else:
            if not feeds or isinstance(feeds[0], dict):
                per_host = [list(feeds)] * n_hosts
            else:
                per_host = [list(f) for f in feeds]
                if len(per_host) != n_hosts:
                    raise ValueError(
                        "per-host feeds: expected %d lists, got %d"
                        % (n_hosts, len(per_host)))
            if len({len(f) for f in per_host}) > 1:
                raise ValueError("every host needs the same number of "
                                 "steps (lockstep collectives)")
        results = [None] * n_hosts
        errors = [None] * n_hosts
        self._run_seq += 1
        run_tag = "r%d." % self._run_seq

        def host_main(hid):
            from . import resilience
            try:
                with resilience.context(host=hid):
                    results[hid] = self._host_loop(hid, run_tag,
                                                   per_host[hid],
                                                   fetch_list,
                                                   steps=steps)
            except BaseException as e:   # surfaced after join
                errors[hid] = e

        threads = [threading.Thread(target=host_main, args=(hid,),
                                    name="pod-host-%d" % hid)
                   for hid in range(n_hosts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        real = [e for e in errors
                if e is not None and not isinstance(e, CoordinationError)]
        if real:
            raise real[0]
        coord = [e for e in errors if e is not None]
        if coord:
            raise coord[0]
        return results

    def _host_loop(self, hid, run_tag, feeds, fetch_list, steps=None):
        # host_id mode holds only THIS host's trainer; simulation mode
        # holds all of them, indexed by the logical host id
        trainer = self._trainers[0] if self._host_id is not None \
            else self._trainers[hid]
        feed = trainer._feed if feeds is None else None
        co = self._coordinator
        fetch_list = trainer._resolved_fetch_list(fetch_list)
        n = int(steps) if feed is not None else len(feeds)
        trainer._require_fresh_dir()
        trainer._save(0)
        co.barrier(run_tag + "pod_start", hid)
        # seed the buddy mailboxes at gen 0 (after the barrier: the
        # ring must be derived from a membership every host agrees
        # on) so a round-1 fault is already buddy-recoverable. reset=
        # because a SECOND run() on the same coordinator starts a new
        # trajectory below the previous run's mailbox generations.
        self._buddy_send(co, hid, trainer, sorted(co.live_hosts()), 0,
                         feed, reset=True)
        if n == 0:
            co.barrier(run_tag + "pod_end", hid)
            return []
        all_fetches = [None] * n
        ckpt_every = trainer._checkpoint_every
        step, restarts, rnd = 0, 0, 0
        while step < n:
            rnd += 1   # advances identically on every host: round names
            #            line up without any out-of-band numbering
            until_ckpt = ckpt_every - (step % ckpt_every)
            w = min(trainer._steps_per_dispatch, n - step, until_ckpt)
            status, err, outs = "ok", None, None
            try:
                if feed is not None:
                    # per-host stream: ≤ w batches (fewer at the drain
                    # tail); the window COUNT still advances by w on
                    # every host, so checkpoint boundaries stay lockstep.
                    # The window filter drops pod-agreed poison batches
                    # on replay (numeric_policy="rewind").
                    outs = trainer._dispatch_window(feed.draw(w), step,
                                                    fetch_list)
                else:
                    outs = trainer._dispatch(feeds, step, w, fetch_list)
                    if (step + w) % ckpt_every == 0 or step + w == n:
                        trainer._save(step + w)
            except Exception as e:
                err = e
                status = "transient" if trainer._policy.is_transient(e) \
                    else "fatal"
            payload = status if feed is None \
                else [status, bool(feed.drained)]
            verdicts = co.all_gather("%sw%d" % (run_tag, rnd), hid,
                                     payload)
            statuses = {h: v if isinstance(v, str) else v[0]
                        for h, v in verdicts.items()}
            if any(v == "fatal" for v in statuses.values()):
                record_event("fatal", step=step,
                             error=type(err).__name__ if err else None)
                if err is not None and status == "fatal":
                    raise err
                bad = sorted(h for h, v in statuses.items()
                             if v == "fatal")
                raise CoordinationError(
                    "pod aborted: host(s) %s hit a fatal error at step %d"
                    % (bad, step))
            if all(v == "ok" for v in statuses.values()):
                for i in range(len(outs) if feed is not None else w):
                    all_fetches[step + i] = outs[i]
                step += w
                if feed is not None:
                    # the cursor commits only with the pod's agreement,
                    # and the checkpoint lands AFTER it so the saved
                    # cursor matches the saved params exactly
                    feed.commit()
                    drained = all(isinstance(v, list) and v[1]
                                  for v in verdicts.values())
                    if step % ckpt_every == 0 or step == n or drained:
                        trainer._save(step)
                        feed.record_metrics()
                    if drained:
                        break          # every host's feed is drained
                # every committed boundary refreshes the buddy tier:
                # the mailbox generation tracks the agreed step exactly
                self._buddy_send(co, hid, trainer, sorted(verdicts),
                                 step, feed)
                continue
            # -- pod-wide recovery ------------------------------------
            restarts += 1   # lockstep on every host: the SHARED budget
            if restarts > self._max_restarts:
                record_event("giveup", step=step, restarts=restarts)
                raise RestartBudgetExceededError(
                    "pod restart budget (%d) exhausted at step %d; "
                    "last local error: %r" % (self._max_restarts, step,
                                              err))
            delay = trainer._policy.delay_s(restarts - 1)
            record_event("pod_restart", step=step, restarts=restarts,
                         error=type(err).__name__ if err else None,
                         backoff_s=delay)
            trainer._policy.sleep(delay)
            # numeric_policy="rewind": agree the poison batch so every
            # host's replay skips it — without this only the faulting
            # host would skip and the pod would fall out of lockstep
            self._agree_poison(co, hid, run_tag, rnd, trainer, step,
                               err)
            # WARM path first: the buddy tier holds every host's state
            # at this very boundary (gen == step) — adopting it loses
            # no committed work and reads no disk. Any doubt falls the
            # whole pod back to the consensus rewind below.
            got = self._buddy_restore(co, hid, run_tag, rnd, trainer,
                                      step, sorted(verdicts), feed=feed)
            if got is None:
                from .. import io as io_mod
                report = io_mod.scrub_checkpoint(trainer._ckpt_dir)
                agreed = co.elect_restore_step(
                    hid, report["valid_steps"],
                    name="%se%d" % (run_tag, rnd))
                got = trainer._restore(step=agreed)
                # the disk rewind moved the pod below the mailbox
                # generations (and a poison-batch replay may change
                # the trajectory): re-seed the buddy tier from the
                # restored state, reset= bypassing the rewind fence
                self._buddy_send(co, hid, trainer, sorted(verdicts),
                                 got, feed, reset=True)
            record_event("pod_restore", step=got)
            step = got
        co.barrier(run_tag + "pod_end", hid)
        if feed is not None:
            # committed per-batch fetches, drain-tail holes removed
            return [o for o in all_fetches if o is not None]
        return all_fetches


# ---------------------------------------------------------------------------
# elastic training: continue on the survivors, re-absorb on rejoin
# ---------------------------------------------------------------------------

def _default_lr_rescale(trainer, scale_by, scope):
    """Default lr_rescale hook: multiply every optimizer learning-rate
    variable in the scope (the ``learning_rate*`` globals the Optimizer
    base creates) by ``scale_by``. Replace via
    ``ElasticTrainer(lr_rescale_hook=...)`` for schedules that live
    elsewhere (e.g. a host-side scheduler object)."""
    import numpy as np
    for name in list(scope.keys()):
        if "learning_rate" not in name:
            continue
        val = scope.find_var(name)
        if val is None:
            continue
        arr = np.asarray(val)
        if not np.issubdtype(arr.dtype, np.floating):
            continue
        scope.set_var(name, (arr * arr.dtype.type(scale_by)))

class ElasticTrainer(PodResilientTrainer):
    """Elastic continue: survivors keep training when a host drops.

    :class:`PodResilientTrainer` answers every fault with pod-wide
    rewind-and-replay. ElasticTrainer upgrades MEMBERSHIP changes to
    elastic semantics while keeping the rewind for poisoned state:

      * **Shrink.** A lost host is fenced exactly as before (no split
        brain), but the survivors do NOT rewind to a checkpoint: they
        complete the in-flight window, re-shard every NamedSharding-
        annotated param/optimizer leaf onto the capacity-scaled mesh
        (:func:`distributed.mesh.reshard_state` — a ``dp``-axis resize
        is one sharded device_put per leaf; gather-then-reshard is the
        general fallback), re-target their CompiledProgram
        (``set_mesh_axes``) and continue from the in-flight step at
        reduced capacity. The Executor's step cache is keyed by the
        mesh axes, so shrink -> grow -> shrink re-uses executables.
        Because the feed batch is sharded over ``dp``, each surviving
        slice automatically takes a LARGER share of the same global
        batch — global batch semantics (and therefore the LR schedule)
        are preserved without touching the optimizer.
      * **Grow.** A fenced host that comes back announces itself
        (``Coordinator.announce_join``); every survivor observes the
        pending set on the window status exchange, so all of them admit
        the same joiner in the same window (``Coordinator.admit`` /
        ``join``: un-fence, barrier, elect the sync step). The live
        state is then shipped to the joiner — directly between scopes
        in the threaded simulation, or through a scrub-validated sync
        checkpoint in ``sync_dir`` (required for ``host_id`` mode,
        where peers are other processes) — and the mesh re-absorbs the
        host (:func:`distributed.mesh.absorb_hosts`). Step counter and
        global batch math line up with an uninterrupted run.
      * **Transient compute faults** (preemptions, NaN blowups, torn
        checkpoints) still take the parent's pod-wide consensus rewind
        — elasticity is for membership, not for poisoned state. The
        restore re-shards onto the CURRENT mesh (``shardings=``), so a
        checkpoint written at full capacity restores onto a shrunk pod.

    Feeds must be the replicated shape (one list of per-step feed
    dicts): every host carries the full global batch and the mesh
    decides each host's share, which is what makes capacity changes a
    pure re-partitioning. Per-host feed streams would need a data-plane
    re-balancer to preserve the global batch — out of scope here.

    Events: ``elastic_shrink`` / ``elastic_grow`` / ``elastic_drain``
    with ``capacity`` labels (plus the mesh/reshard events) land in the
    resilience log and therefore in ``resilience.metrics()``.

    ``drain_after=k`` arms the PROACTIVE straggler drain: each host's
    critical-straggler latch (``StragglerDetector(action_k=)``) rides
    the window status exchange, and a host the pod saw flagged for k
    consecutive windows is admitted as a PLANNED loss at the next
    window boundary — the rejoin barriers in reverse: agree the drain
    from the frozen verdicts, the straggler fences itself, the
    survivors shrink — instead of every host stalling until the
    straggler becomes a hard ``CollectiveTimeoutError``.
    """

    # checkpointed marker var: the LR-rescale factor currently applied
    # to the scope's learning rates. It travels WITH the state (saved by
    # save_checkpoint, shipped on rejoin), so a restore of a checkpoint
    # taken under a different capacity can reconcile exactly.
    LR_SCALE_VAR = "@lr_rescale_factor"

    def __init__(self, trainers, coordinator=None, max_restarts=3,
                 host_id=None, rejoin=True, sync_dir=None,
                 lr_rescale=False, grad_merge_steps=1,
                 lr_rescale_hook=None, drain_after=None,
                 ship_compress="zlib", drain_floor=None,
                 drain_cooldown=None, drain_hb_lag_s=None,
                 drain_stream_lag=None, sdc_detect=None,
                 pp_recut=True, buddy=True, buddy_compress="zlib",
                 buddy_p2p=True, buddy_delta=True,
                 buddy_rebase_every=8):
        super(ElasticTrainer, self).__init__(
            trainers, coordinator=coordinator, max_restarts=max_restarts,
            host_id=host_id, buddy=buddy, buddy_compress=buddy_compress,
            buddy_p2p=buddy_p2p, buddy_delta=buddy_delta,
            buddy_rebase_every=buddy_rebase_every)
        self._rejoin = bool(rejoin)
        # pp_recut=True (default): a host loss on a >1 pp mesh re-cuts
        # the K logical stages over the surviving slots (multiple
        # stages per slot — distributed/pipeline_program.recut_plan)
        # instead of taking the consensus rewind, whenever the
        # survivors can still hold every stage. False restores the
        # PR 10 behavior: every pp host loss rewinds on the unchanged
        # mesh (elastic_pp_rewind carries reason="disabled").
        self._pp_recut = bool(pp_recut)
        self._sync_dir = sync_dir
        # ship_compress: codec for the rejoin state ship (ops/quant_ops
        # host codec in the threaded simulation, io.save_checkpoint
        # compress= in sync_dir mode). "zlib" (default) is LOSSLESS —
        # the joiner's state stays bitwise the donors', which the
        # pod-parity guarantees rely on; "q8" is the lossy block codec
        # for operators who accept its error envelope on rejoin; None
        # ships full-width. Either way the raw-vs-wire pair lands in
        # resilience.bytes_totals()["stateship"].
        if ship_compress not in (None, "zlib", "q8"):
            raise ValueError("ship_compress must be None, 'zlib' or "
                             "'q8', got %r" % (ship_compress,))
        self._ship_compress = ship_compress
        # drain_after=k arms the PROACTIVE straggler drain: each host's
        # critical-straggler latch (StragglerDetector action_k) rides
        # the window status exchange; a host flagged for k CONSECUTIVE
        # windows is drained at the next window boundary — the pod
        # agrees the drain from the same frozen verdicts, the straggler
        # fences itself (a planned loss), and the survivors take the
        # ordinary elastic-shrink path instead of stalling until the
        # straggler becomes a CollectiveTimeoutError. None disables.
        if drain_after is not None and int(drain_after) < 1:
            raise ValueError("drain_after must be >= 1 consecutive "
                             "critical-straggler windows (or None)")
        self._drain_after = None if drain_after is None \
            else int(drain_after)
        # straggler-aware drain policy (the ROADMAP carry-over): the
        # latch that rides the exchange is no longer compute-only.
        #   drain_hb_lag_s:   a host whose heartbeat-cadence lag gauge
        #                     (transport_heartbeat_lag) exceeds this
        #                     many seconds counts flagged — NETWORK
        #                     stragglers drain too. None disables.
        #   drain_stream_lag: a host whose agreed feed stream lag
        #                     (feed_stream_lag, committed samples
        #                     behind the most-advanced host) exceeds
        #                     this counts flagged — DATA stragglers
        #                     drain too. None disables.
        #   drain_floor:      never drain below this capacity — an int
        #                     is an absolute minimum of live hosts, a
        #                     float in (0, 1] a fraction of the full
        #                     pod. None keeps the historical floor of
        #                     one surviving host.
        #   drain_cooldown:   at most ONE host drained per this many
        #                     windows (None = drain_after): the
        #                     post-shrink pod must re-observe before a
        #                     second victim is even considered, so a
        #                     systemic slowdown can never cascade into
        #                     serial drains.
        # All four decisions are computed from the FROZEN window
        # verdicts, so every live host agrees on them exactly.
        if drain_floor is not None:
            if isinstance(drain_floor, float):
                if not 0.0 < drain_floor <= 1.0:
                    raise ValueError(
                        "drain_floor as a fraction must be in (0, 1], "
                        "got %r" % drain_floor)
            elif int(drain_floor) < 1:
                raise ValueError("drain_floor as a host count must be "
                                 ">= 1, got %r" % drain_floor)
        self._drain_floor = drain_floor
        if drain_cooldown is not None and int(drain_cooldown) < 1:
            raise ValueError("drain_cooldown must be >= 1 windows "
                             "(or None = drain_after)")
        self._drain_cooldown = self._drain_after \
            if drain_cooldown is None and self._drain_after \
            else (None if drain_cooldown is None
                  else int(drain_cooldown))
        if drain_hb_lag_s is not None and float(drain_hb_lag_s) <= 0:
            raise ValueError("drain_hb_lag_s must be > 0 seconds "
                             "(or None to ignore heartbeat lag)")
        self._drain_hb_lag_s = None if drain_hb_lag_s is None \
            else float(drain_hb_lag_s)
        if drain_stream_lag is not None and float(drain_stream_lag) <= 0:
            raise ValueError("drain_stream_lag must be > 0 samples "
                             "(or None to ignore feed stream lag)")
        self._drain_stream_lag = None if drain_stream_lag is None \
            else float(drain_stream_lag)
        # sdc_detect arms the silent-data-corruption sweep: every
        # window each host publishes its float-state L2 norm on the
        # status exchange and every host runs the SAME pod-median
        # outlier test (resilience.SDCDetector) over the frozen map.
        # A host whose norm deviates for the detector's `consecutive`
        # windows is a SUSPECTED-SDC host: flagged into the proactive
        # drain latch, so with drain_after armed the pod drains it
        # like any critical straggler (the corruption it would keep
        # feeding the collectives is worse than losing its capacity).
        # True = default detector; a dict = SDCDetector kwargs. The
        # detector is instantiated PER HOST LOOP from the same config
        # and fed the same frozen verdicts, so every host's suspect
        # set agrees without any extra exchange.
        if sdc_detect in (None, False):
            self._sdc_cfg = None
        elif sdc_detect is True:
            self._sdc_cfg = {}
        elif isinstance(sdc_detect, dict):
            self._sdc_cfg = dict(sdc_detect)
        else:
            raise ValueError(
                "sdc_detect must be None/False, True, or a dict of "
                "SDCDetector kwargs, got %r" % (sdc_detect,))
        # lr_rescale=True: the FIXED-PER-HOST-BATCH regime (per-host
        # feed streams — the global batch shrinks with the dp axis), so
        # capacity changes linearly rescale the learning rate,
        # gradient-merge-aware: grad_merge_steps may be an int or a
        # callable live_hosts -> k for schedules that re-grow the
        # global batch by accumulating more micro-batches per update.
        # The default False is the replicated-feed regime, where a
        # capacity change re-partitions the SAME global batch and the
        # LR schedule must not move.
        self._lr_rescale = bool(lr_rescale)
        self._grad_merge_steps = grad_merge_steps
        self._lr_rescale_hook = lr_rescale_hook
        self._nonces = {}
        self._nonce_lock = threading.Lock()
        # the FULL topology per trainer, frozen at first use:
        # set_mesh_axes mutates the strategy, so re-reading it on a
        # later run() after a run that ended shrunk would compound the
        # capacity scaling (dp = shrunk*live//total)
        self._frozen_axes = {}
        if host_id is not None and rejoin and sync_dir is None:
            raise ValueError(
                "host_id mode cannot ship rejoin state between process "
                "scopes — pass sync_dir= (a shared directory the "
                "survivors write the sync checkpoint to)")

    def run(self, feeds, fetch_list=None, steps=None):
        if feeds is not None:
            feeds = list(feeds)
            if self._host_id is None and feeds \
                    and not isinstance(feeds[0], dict):
                raise ValueError(
                    "ElasticTrainer needs the replicated feed shape "
                    "(ONE list of per-step feed dicts): every host "
                    "carries the full global batch and the dp mesh "
                    "assigns each host its share, which is what makes "
                    "a capacity change a pure re-partitioning. For "
                    "per-host streams attach a reader.ShardedFeed to "
                    "every trainer and call run(feeds=None, steps=N) — "
                    "the coordinator re-balances the streams on every "
                    "membership change")
        return super(ElasticTrainer, self).run(feeds, fetch_list,
                                               steps=steps)

    # -- topology helpers --------------------------------------------------
    @staticmethod
    def _target_strategy(trainer):
        from .compiler import CompiledProgram
        t = trainer._target
        return t if isinstance(t, CompiledProgram) else None

    def _current_shardings(self, trainer):
        """{var: NamedSharding} of every scope var over the trainer's
        CURRENT mesh — what re-shards an exact-step restore (or a
        shipped sync checkpoint) straight onto a resized topology."""
        strategy = self._target_strategy(trainer)
        if strategy is None:
            return None
        mesh = strategy._mesh_obj()
        sc = self._scope_of(trainer)
        return {name: strategy._var_sharding(name, mesh)
                for name in list(sc.keys())}

    def _next_nonce(self, hid):
        with self._nonce_lock:
            self._nonces[hid] = self._nonces.get(hid, 0) + 1
            return self._nonces[hid]

    def _straggler_flag(self, hid):
        """This host's critical-straggler latch for the window status
        exchange (and the pre-emptive straggler_ckpt). In production
        there is one process-global detector per real host; the
        threaded simulation SHARES the latch between simulated hosts,
        so tests that need deterministic attribution override this
        seam."""
        from . import watchdog
        return watchdog.straggler_action_due()

    @staticmethod
    def _agreed_lags(verdicts):
        """Per-host stream-lag snapshot assembled from the FROZEN
        window verdicts (each host's ``exchange_state()["lag"]``).
        Every live host computes this from the same frozen round, so
        the map is identical pod-wide — the agreed input that makes
        ``ShardedFeed(weighted_rebalance=True)`` safe on socket pods
        with divergent local event logs. None when the exchange
        carried no lags (pre-upgrade peers): rebalance then falls back
        to its local-gauge default."""
        lags = {}
        for h, v in verdicts.items():
            exch = v[2] if len(v) > 2 else None
            if isinstance(exch, dict) and "lag" in exch:
                lags[h] = float(exch["lag"])
        return lags or None

    def _hb_lag(self, hid):
        """This host's heartbeat-cadence lag (the value behind the
        transport_heartbeat_lag gauge) for the window exchange — 0.0
        on coordinators without a transport client (Local/File)."""
        client = getattr(self._coordinator, "_client", None)
        try:
            return float(getattr(client, "hb_lag_s", 0.0) or 0.0)
        except (TypeError, ValueError):   # pragma: no cover - foreign
            return 0.0

    def _drain_floor_hosts(self):
        """Minimum live hosts that must REMAIN after a drain."""
        f = self._drain_floor
        if f is None:
            return 1
        if isinstance(f, float):
            import math
            return max(1, int(math.ceil(f * self._coordinator.n_hosts)))
        return max(1, int(f))

    @staticmethod
    def _sdc_norm(trainer):
        """This host's state-norm signal for the SDC sweep: the L2
        norm over every floating scope var (params + optimizer
        moments), accumulated in float64 in sorted-name order so
        identical states produce identical norms. In the replicated-
        feed regime healthy replicas are BITWISE identical, so any
        silent corruption — even one flipped mantissa bit — moves
        this host's norm off the pod median while the median's MAD
        stays ~0; per-host-stream pods fall back to the detector's
        threshold test. A NaN norm counts as an outlier outright."""
        import numpy as np
        sc = ElasticTrainer._scope_of(trainer)
        total = 0.0
        for name in sorted(sc.keys()):
            val = sc.find_var(name)
            if val is None:
                continue
            arr = np.asarray(val)
            if not np.issubdtype(arr.dtype, np.floating):
                continue
            total += float(np.sum(np.square(arr.astype(np.float64))))
        return float(np.sqrt(total))

    def _drain_flags(self, verdicts, sdc=None):
        """Per-host straggler flags for this window, computed from the
        FROZEN verdicts only (identical on every live host): the
        compute latch (v[3]), the heartbeat-cadence lag it carried
        (v[4], vs drain_hb_lag_s), the agreed feed stream lag
        (vs drain_stream_lag) and — when the SDC sweep is armed — the
        detector's suspect set (itself fed from frozen verdicts, so
        it agrees pod-wide too). Pre-upgrade peers' shorter payloads
        simply contribute no new signals."""
        lags = self._agreed_lags(verdicts) or {}
        suspects = sdc.suspects() if sdc is not None else ()
        flags = {}
        for h, v in verdicts.items():
            f = bool(v[3]) if len(v) > 3 else False
            if not f and self._drain_hb_lag_s is not None and len(v) > 4:
                try:
                    f = float(v[4] or 0.0) > self._drain_hb_lag_s
                except (TypeError, ValueError):
                    f = False
            if not f and self._drain_stream_lag is not None \
                    and h in lags:
                f = lags[h] > self._drain_stream_lag
            if not f and h in suspects:
                f = True
            flags[h] = f
        return flags

    # -- gradient-merge-aware LR rescale (fixed-per-host-batch regime) ----
    def _grad_merge_k(self, n_live):
        k = self._grad_merge_steps
        return int(k(n_live)) if callable(k) else int(k)

    def _lr_target_factor(self, n_live):
        """Linear-scaling target: effective global batch is per-host
        batch x live hosts x gradient-merge steps; the factor is its
        ratio to the full-capacity global batch. An operator who bumps
        grad_merge_steps to re-fill the global batch on a shrink
        (callable k) gets factor 1.0 — no LR move — automatically."""
        n_total = self._coordinator.n_hosts
        k_live = self._grad_merge_k(n_live)
        k_full = self._grad_merge_k(n_total)
        return (n_live * k_live) / float(n_total * k_full), k_live

    def _apply_lr_scale(self, trainer, live):
        """Reconcile the scope's learning rates with the CURRENT
        capacity. Idempotent and restore-safe: the applied factor lives
        in a checkpointed scope var, so a rewind that restores an LR
        saved under different capacity is re-scaled by exactly the
        missing ratio."""
        if not self._lr_rescale:
            return
        import numpy as np
        sc = self._scope_of(trainer)
        cur = sc.find_var(self.LR_SCALE_VAR)
        cur = 1.0 if cur is None else float(np.asarray(cur))
        target, k_live = self._lr_target_factor(len(live))
        if abs(target - cur) < 1e-9:
            return
        rel = target / cur
        hook = self._lr_rescale_hook or _default_lr_rescale
        hook(trainer, rel, sc)
        # float64: a float32 marker would round non-dyadic ratios
        # (e.g. 5/6) past the tolerance and re-trigger a tiny spurious
        # rescale on every later retarget/restore
        sc.set_var(self.LR_SCALE_VAR, np.float64(target))
        record_event("lr_rescale",
                     capacity="%d/%d" % (len(live),
                                         self._coordinator.n_hosts),
                     factor=round(target, 6), rel=round(rel, 6),
                     grad_merge=k_live)

    @staticmethod
    def _pp_axes(axes):
        """True when the trainer's FULL topology carries a >1 pipeline
        axis — stage state is stacked on pp; host loss either RE-CUTS
        the stages over the surviving slots (pp_recut=True and
        feasible) or takes the consensus-rewind path."""
        return bool(axes) and int(axes.get("pp") or 1) > 1

    def _pp_stage_signatures(self, trainer):
        """Per-stage structural signatures of the trainer's stamped
        forward ops (None when unstamped — the auto-cut already proved
        homogeneity). Fed to recut_plan so a heterogeneous cut is a
        TYPED refusal (reason=heterogeneous_stages), never a broken
        super-stage."""
        from ..distributed import pipeline_program as ppp
        strategy = self._target_strategy(trainer)
        if strategy is None:
            return None
        staged = {}
        for op in strategy._program.global_block().ops:
            s = op.attrs.get("pp_stage")
            if s is not None:
                staged.setdefault(int(s), []).append(op)
        if not staged:
            return None
        return [ppp._stage_signature(staged[s]) for s in sorted(staged)]

    def _pp_recut_decision(self, trainer, base_axes, n_live):
        """(n_slots, reason) for a pp host loss at the frozen live
        count: the slot count a re-cut would target, or None with the
        typed reason the pod must rewind instead (disabled |
        infeasible_slots | heterogeneous_stages). Deterministic in
        (base_axes, n_live), so every host that gathered the same
        frozen verdicts decides the same way."""
        from ..distributed import pipeline_program as ppp
        if not self._pp_recut:
            return None, "disabled"
        k = int(base_axes.get("pp") or 1)
        n_total = self._coordinator.n_hosts
        # slots scale with capacity like the dp axis does — and a host
        # loss must shrink the ring by at least one slot (survivors
        # cannot keep a slot the dead host owned)
        n_slots = min(k - 1, max(1, k * n_live // n_total))
        if n_slots < ppp.recut_min_slots(k):
            # below the K-1..ceil(K/2) contract: more than two stages
            # per slot — the super-stage compute/stash growth is
            # unbounded, so the pod rewinds and waits for capacity
            return None, "infeasible_slots"
        try:
            ppp.recut_plan(k, n_slots,
                           stage_signatures=self._pp_stage_signatures(
                               trainer))
        except ppp.PPRecutError as e:
            return None, e.reason
        return n_slots, None

    def _retarget(self, trainer, base_axes, live, kind, **fields):
        """Re-shard this host's live state onto the capacity-scaled mesh
        and record the elastic event. base_axes is the FULL topology —
        scaling is always from it, never compounded."""
        from ..distributed import mesh as mesh_mod
        n_total = self._coordinator.n_hosts
        capacity = "%d/%d" % (len(live), n_total)
        strategy = self._target_strategy(trainer)
        if strategy is None or not base_axes:
            record_event(kind, capacity=capacity, resharded=0, **fields)
            self._apply_lr_scale(trainer, live)
            return
        if self._pp_axes(base_axes):
            k = int(base_axes.get("pp") or 1)
            bs = strategy._build_strategy
            cur = getattr(bs, "pp_recut_slots", None)
            want = fields.pop("recut_slots", None)
            if want is None and cur is not None \
                    and len(live) >= n_total:
                # RE-GROW: every host is back — return to the
                # 1-stage-per-slot plan at this window boundary (the
                # cache token keyed the full-plan executable, so the
                # grow re-uses it instead of recompiling)
                want = k
            if want is None or want == (cur if cur is not None else k):
                # pipeline mesh at an unchanged cut: the mesh and
                # shardings stay put; capacity changes only move data
                # lanes and the LR scale.
                record_event(kind, capacity=capacity, resharded=0,
                             pp=True, **fields)
                self._apply_lr_scale(trainer, live)
                return
            # RE-CUT (or re-grow): the K logical stages re-stack over
            # `want` mesh slots. The scope keeps the flat per-stage
            # layout — only the mesh and the in-jit stacking geometry
            # change, so this is a set_mesh_axes + state re-placement,
            # never a state rewrite.
            t0 = time.monotonic()
            axes = dict(base_axes)
            axes["pp"] = want
            bs.pp_recut_slots = None if want == k else want
            old_mesh = strategy._mesh_obj()
            strategy.set_mesh_axes(axes)
            new_mesh = strategy._mesh_obj()
            moved = 0
            if new_mesh != old_mesh:
                sc = self._scope_of(trainer)
                new_state = mesh_mod.reshard_state(dict(sc.items()),
                                                   old_mesh, new_mesh)
                for name, val in new_state.items():
                    if val is not sc.find_var(name):
                        sc.set_var(name, val)
                        moved += 1
            record_event(kind, capacity=capacity,
                         mesh={a: int(s)
                               for a, s in new_mesh.shape.items()},
                         resharded=moved, pp=True, pp_slots=want,
                         pp_stages=k,
                         latency_s=round(time.monotonic() - t0, 6),
                         **fields)
            self._apply_lr_scale(trainer, live)
            return
        axes = dict(base_axes)
        if "dp" in axes and axes["dp"] > 1 and len(live) < n_total:
            axes["dp"] = max(1, axes["dp"] * len(live) // n_total)
        old_mesh = strategy._mesh_obj()
        strategy.set_mesh_axes(axes)
        new_mesh = strategy._mesh_obj()
        moved = 0
        if new_mesh != old_mesh:
            sc = self._scope_of(trainer)
            new_state = mesh_mod.reshard_state(dict(sc.items()),
                                               old_mesh, new_mesh)
            for name, val in new_state.items():
                if val is not sc.find_var(name):
                    sc.set_var(name, val)
                    moved += 1
        record_event(kind, capacity=capacity,
                     mesh={a: int(s) for a, s in new_mesh.shape.items()},
                     resharded=moved, **fields)
        self._apply_lr_scale(trainer, live)

    # -- state shipping ----------------------------------------------------
    def _ship_state(self, hid, trainer, live, joined, sync_step):
        """Donor half: make the live state reachable by the joiner. In
        sync_dir mode the LOWEST surviving host writes a checkpoint at
        the sync step; in the threaded simulation the joiner reads the
        donor's scope directly, so there is nothing to do here."""
        if self._sync_dir is None:
            return
        donors = [h for h in live if h != joined]
        if hid != min(donors):
            return
        from .. import io as io_mod
        feed_state = None if trainer._feed is None \
            else trainer._feed.global_state()
        io_mod.save_checkpoint(trainer._executor, self._sync_dir,
                               trainer._program, step=sync_step,
                               keep_last=2, scope=self._scope_of(trainer),
                               feed_state=feed_state,
                               compress=self._ship_compress)
        try:
            raw, wire = io_mod.checkpoint_dir_bytes(self._sync_dir,
                                                    sync_step)
            resilience.record_bytes("stateship", raw, wire)
        except (OSError, ValueError, KeyError):  # pragma: no cover
            pass   # accounting must never fail a rejoin
        record_event("sync_ship", step=sync_step)

    def _receive_state(self, hid, trainer, live, sync_step):
        """Joiner half: adopt the pod's CURRENT state (scrub-validated
        when it travels via sync_dir). With a feed attached, the agreed
        pod-wide cursor map comes along on the same barrier — the
        admitted host takes its stream lanes back from the survivors at
        the exact committed positions."""
        import numpy as np
        import jax
        sc = self._scope_of(trainer)
        feed = trainer._feed
        if self._sync_dir is not None:
            from .. import io as io_mod
            report = io_mod.scrub_checkpoint(self._sync_dir)
            if sync_step not in report["valid_steps"]:
                raise CoordinationError(
                    "sync checkpoint for step %d is not scrub-valid in "
                    "%s (valid: %s) — refusing to rejoin from damaged "
                    "state" % (sync_step, self._sync_dir,
                               report["valid_steps"]))
            got = io_mod.load_checkpoint(
                trainer._executor, self._sync_dir, trainer._program,
                step=sync_step, scope=sc,
                shardings=self._current_shardings(trainer),
                with_feed_state=feed is not None)
            if feed is not None:
                _step, feed_state = got
                if feed_state is None:
                    raise CoordinationError(
                        "sync checkpoint for step %d in %s carries no "
                        "feed cursor — the donor must ship the data "
                        "position with the params" % (sync_step,
                                                      self._sync_dir))
                feed.restore(feed_state, live=sorted(live))
            try:
                raw, wire = io_mod.checkpoint_dir_bytes(self._sync_dir,
                                                        sync_step)
                resilience.record_bytes("stateship", raw, wire)
            except (OSError, ValueError, KeyError):  # pragma: no cover
                pass
            return
        donor = self._trainers[min(h for h in live if h != hid)]
        if feed is not None:
            feed.restore(donor._feed.global_state(), live=sorted(live))
        # threaded simulation: the donor's leaves cross "the wire"
        # through the ops/quant_ops host codec (zlib = lossless deflate,
        # q8 = lossy block codec) so the byte accounting — and, for q8,
        # the accuracy envelope — matches what a real transport would see
        from ..ops import quant_ops
        raw_total, wire_total = 0, 0
        for name, val in dict(self._scope_of(donor).items()).items():
            if isinstance(val, jax.Array):
                # fresh buffers, same layout: sharing the donor's arrays
                # would die the moment its next step DONATES them
                host = np.asarray(val)
                if self._ship_compress is not None:
                    enc = quant_ops.encode_array(host,
                                                 self._ship_compress)
                    raw_total += enc["raw_bytes"]
                    wire_total += enc["wire_bytes"]
                    host = quant_ops.decode_array(enc)
                sc.set_var(name, jax.device_put(host, val.sharding))
            else:
                sc.set_var(name, val)
        if wire_total:
            resilience.record_bytes("stateship", raw_total, wire_total)

    # -- the elastic host loop ---------------------------------------------
    def _host_loop(self, hid, run_tag, feeds, fetch_list, steps=None):
        from . import resilience, watchdog
        trainer = self._trainers[0] if self._host_id is not None \
            else self._trainers[hid]
        feed = trainer._feed if feeds is None else None
        co = self._coordinator
        fetch_list = trainer._resolved_fetch_list(fetch_list)
        n = int(steps) if feed is not None else len(feeds)
        strategy = self._target_strategy(trainer)
        key = 0 if self._host_id is not None else hid
        if key not in self._frozen_axes:
            self._frozen_axes[key] = dict(
                strategy._build_strategy.mesh_axes or {}) \
                if strategy is not None else {}
        base_axes = self._frozen_axes[key]
        trainer._require_fresh_dir()
        trainer._save(0)
        co.barrier(run_tag + "pod_start", hid)
        # seed the buddy mailboxes at gen 0 (post-barrier membership =
        # the agreed ring); reset= because a second run() on the same
        # coordinator starts below the previous run's generations
        self._buddy_send(co, hid, trainer, sorted(co.live_hosts()), 0,
                         feed, reset=True)
        if n == 0:
            co.barrier(run_tag + "pod_end", hid)
            return []
        all_fetches = [None] * n

        def result():
            if feed is not None:
                # committed per-batch fetches in window order (holes
                # are windows this host missed while fenced or drained)
                return [o for o in all_fetches if o is not None]
            return all_fetches

        ckpt_every = trainer._checkpoint_every
        step, restarts, rnd = 0, 0, 0
        known_live = sorted(co.live_hosts())
        # proactive-drain accounting: per-host consecutive windows the
        # critical-straggler flag was up, plus windows since the last
        # drain (the cooldown clock; None = never drained). Local to
        # this host's loop — every host computes both from the same
        # frozen verdicts, so the decisions agree pod-wide.
        strag_counts = {}
        since_drain = None
        # SDC sweep: one detector per host loop, every instance fed
        # the same frozen norm map — suspect sets agree pod-wide with
        # no extra exchange (see sdc_detect in __init__)
        sdc = None if self._sdc_cfg is None \
            else resilience.SDCDetector(**self._sdc_cfg)
        while step < n:
            rnd += 1
            until_ckpt = ckpt_every - (step % ckpt_every)
            w = min(trainer._steps_per_dispatch, n - step, until_ckpt)
            status, err, outs = "ok", None, None
            try:
                if feed is not None:
                    # the boundary save moves AFTER the status exchange:
                    # the checkpoint must carry the agreed cursor map at
                    # this exact boundary, which only exists once every
                    # live host's window cursor has been gathered. The
                    # window filter drops pod-agreed poison batches on
                    # replay (numeric_policy="rewind").
                    outs = trainer._dispatch_window(feed.draw(w), step,
                                                    fetch_list)
                else:
                    outs = trainer._dispatch(feeds, step, w, fetch_list)
                    if (step + w) % ckpt_every == 0 or step + w == n:
                        trainer._save(step + w)
            except resilience.SimulatedHostDeathError as e:
                # THIS host is going away (eviction notice). Fence
                # ourselves so the survivors' next gather continues
                # without waiting out the timeout, then rejoin (or bow
                # out). An abrupt death skips even this: the gather
                # timeout fences us identically, just slower.
                record_event("host_death", step=step,
                             error=type(e).__name__)
                co.mark_lost(hid, "died at step %d: %s"
                             % (step, type(e).__name__))
                got = self._rejoin_or_exit(hid, run_tag, trainer,
                                           base_axes, step)
                if got is None:
                    return result()             # fenced exit (partial)
                step, rnd, restarts = got
                known_live = sorted(co.live_hosts())
                continue
            except Exception as e:
                err = e
                status = "transient" if trainer._policy.is_transient(e) \
                    else "fatal"
            pending = sorted([int(h), int(nc)] for h, nc in
                             co.pending_joins().items())
            # the cursor rides the status exchange: every host's
            # TENTATIVE post-window position, published to peers only
            # if the window commits (observe below) — a dead host's
            # uncommitted draws are invisible, so its lanes re-home at
            # the last agreed position: nothing lost, nothing doubled
            exch = None if feed is None else feed.exchange_state()
            # this host's critical-straggler latch rides the exchange:
            # the pod-agreed view is what the proactive drain (and the
            # pre-emptive straggler_ckpt below) acts on
            strag = bool(self._straggler_flag(hid))
            # the SDC sweep's norm rides the same exchange (v[5]):
            # computed AFTER the window ran, so this window's silent
            # corruption is already visible in it
            norm = None if sdc is None else self._sdc_norm(trainer)
            try:
                verdicts = co.all_gather("%sw%d" % (run_tag, rnd), hid,
                                         [status, pending, exch, strag,
                                          self._hb_lag(hid), norm])
            except HostLostError:
                # a peer's timeout fenced US (e.g. this host straggled
                # past the collective deadline): stop competing
                record_event("host_fenced", step=step)
                got = self._rejoin_or_exit(hid, run_tag, trainer,
                                           base_axes, step)
                if got is None:
                    return result()
                step, rnd, restarts = got
                known_live = sorted(co.live_hosts())
                continue
            live = sorted(verdicts)
            lost = sorted(set(known_live) - set(live))
            pp_rewind, pp_recut = False, None
            if lost:
                if self._pp_axes(base_axes):
                    # PIPELINE mesh host loss: RE-CUT when the
                    # survivors can still hold every logical stage
                    # (multiple stages per slot — recut_plan), REWIND
                    # otherwise. The decision reads only the frozen
                    # verdicts (live count) and static plan facts, so
                    # every host decides identically; the re-cut
                    # itself waits for the all-ok commit below (the
                    # PR 10 fetch-hole discipline — the survivors'
                    # completed window is kept either way).
                    n_slots, why = self._pp_recut_decision(
                        trainer, base_axes, len(live))
                    all_ok = all(v[0] == "ok"
                                 for v in verdicts.values())
                    if n_slots is not None and all_ok:
                        pp_recut = n_slots
                    else:
                        # consensus rewind (the shared transient tail
                        # below): scrub, elect the common step,
                        # restore, replay bitwise on the unchanged
                        # mesh. reason= tells a policy refusal from a
                        # genuine infeasibility — a faulted window
                        # rewinds regardless of slot feasibility.
                        pp_rewind = True
                        record_event(
                            "elastic_pp_rewind", lost=lost, step=step,
                            capacity="%d/%d"
                            % (len(live), self._coordinator.n_hosts),
                            reason=why if n_slots is None
                            else "faulted_window")
                    known_live = live
                else:
                    # ELASTIC SHRINK: no rewind — re-shard and continue
                    self._retarget(trainer, base_axes, live,
                                   "elastic_shrink", lost=lost, step=step)
                    known_live = live
            statuses = {h: v[0] for h, v in verdicts.items()}
            if any(v == "fatal" for v in statuses.values()):
                record_event("fatal", step=step,
                             error=type(err).__name__ if err else None)
                if err is not None and status == "fatal":
                    raise err
                bad = sorted(h for h, v in statuses.items()
                             if v == "fatal")
                raise CoordinationError(
                    "pod aborted: host(s) %s hit a fatal error at step %d"
                    % (bad, step))
            if all(v == "ok" for v in statuses.values()):
                # ONE commit protocol for both the ordinary window and
                # the pp-rewind window: on a pipeline mesh the
                # SURVIVORS' completed window is still good — keep its
                # fetches and cursor, then take the consensus rewind
                # from the advanced position (the election lands on the
                # newest common checkpoint; a replay refills bitwise).
                # pp_rewind skips only lane re-homing (the rewind tail
                # rebalances before the cursor restore) and this
                # window's admission/drain decisions.
                for i in range(len(outs) if feed is not None else w):
                    all_fetches[step + i] = outs[i]
                step += w
                if feed is not None:
                    # the pod agreed: publish this window's cursor,
                    # adopt the peers' (they committed the same way),
                    # then — on a shrink — deterministically re-home
                    # the lost host's lanes across the survivors
                    feed.commit()
                    for h, v in verdicts.items():
                        if h != hid:
                            feed.observe(v[2])
                    if lost and not pp_rewind:
                        # weighted placement reads the AGREED lag map
                        # carried on this very exchange, never the
                        # host-local gauges (socket pods diverge)
                        feed.rebalance(live,
                                       lags=self._agreed_lags(verdicts))
                    if step % ckpt_every == 0 or step == n \
                            or feed.all_drained():
                        # all_drained: the break below must leave the
                        # final committed batches checkpointed, not
                        # trailing the returned results
                        trainer._save(step)
                        feed.record_metrics()
                if strag and step % ckpt_every != 0 and step != n:
                    trainer._save(step)
                    record_event("straggler_ckpt", step=step)
                if not pp_rewind and pp_recut is None:
                    # buddy send rides the committed boundary, ringed
                    # over THIS round's frozen live set (an elastic
                    # shrink re-rings automatically). A pp-loss round
                    # SKIPS it: the lost host's mailbox is pinned at
                    # the previous boundary and the rewind tail below
                    # needs every owner at that same generation —
                    # survivors advancing would turn a recoverable
                    # loss into buddy_stale
                    self._buddy_send(co, hid, trainer, live, step,
                                     feed)
            if pp_recut is not None:
                # RE-CUT at the committed boundary: the survivors'
                # all-ok window is already committed above, so the
                # re-stacked plan starts from an agreed position. A
                # fault here — the coordination.recut failpoint, or a
                # real failure inside the retarget — falls back to the
                # budget-free consensus rewind on the RESTORED full
                # plan: never a crash, never a silent shrink.
                from . import faultinject
                try:
                    faultinject.hit("coordination.recut",
                                    {"step": step, "slots": pp_recut},
                                    host=hid)
                    self._retarget(trainer, base_axes, live,
                                   "elastic_pp_recut", lost=lost,
                                   step=step, recut_slots=pp_recut)
                    # re-cut committed: refresh the buddy tier over
                    # the re-stacked membership at this boundary
                    self._buddy_send(co, hid, trainer, live, step,
                                     feed)
                except Exception as e:
                    pp_rewind = True
                    st = self._target_strategy(trainer)
                    if st is not None:
                        # undo any half-applied mesh move before the
                        # rewind: the restore's shardings come from the
                        # CURRENT strategy, which must be the full
                        # 1-stage-per-slot plan again
                        st._build_strategy.pp_recut_slots = None
                        st.set_mesh_axes(dict(base_axes))
                    record_event(
                        "elastic_pp_rewind", lost=lost, step=step,
                        capacity="%d/%d"
                        % (len(live), self._coordinator.n_hosts),
                        reason="recut_failed", error=type(e).__name__)
            if not pp_rewind and all(v == "ok"
                                     for v in statuses.values()):
                if sdc is not None:
                    # every host folds the SAME frozen norm map into
                    # its detector: suspect sets stay pod-agreed
                    sdc.observe({h: v[5] for h, v in verdicts.items()
                                 if len(v) > 5 and v[5] is not None},
                                step=step)
                # admission rides the window boundary: every live host
                # saw the same gathered pending sets, so they all admit
                # the same joiner (lowest id fully-observed) together
                agreed = agreed_pending(verdicts)
                if agreed is not None:
                    jhid, nonce = agreed
                    try:
                        sync = co.admit(hid, jhid, nonce,
                                        [step, rnd, restarts],
                                        name=run_tag + "join")
                        if sync is not None:
                            live = sorted(co.live_hosts())
                            self._retarget(trainer, base_axes, live,
                                           "elastic_grow",
                                           joined=[jhid], step=step)
                            known_live = live
                            if feed is not None:
                                # give the joiner its stream lanes back
                                # at the same barrier that ships state
                                feed.rebalance(
                                    live,
                                    lags=self._agreed_lags(verdicts))
                            tag = "%s_h%d_n%d" % (run_tag, jhid, nonce)
                            co.barrier("ship" + tag, hid)
                            self._ship_state(hid, trainer, live, jhid,
                                             step)
                            co.barrier("shipped" + tag, hid)
                            # joiner copies between these two barriers:
                            # our scope must not advance under its reads
                            co.barrier("done" + tag, hid)
                            # the admission is a checkpointable event:
                            # the joiner's dir is missing every boundary
                            # saved while it was fenced, so WITHOUT a
                            # fresh common step a later transient
                            # fault's consensus (quorum = all live
                            # hosts) would rewind to the pre-death
                            # history — or NoQuorumError once pruning
                            # evicts it. Boundary steps were already
                            # saved by this window's normal save.
                            if step % ckpt_every != 0 and step != n:
                                trainer._save(step)
                            # the ring changed (the joiner is back):
                            # re-seed every mailbox over the NEW
                            # membership at the common sync step —
                            # reset= because the pod may sit below a
                            # pre-rejoin mailbox generation
                            self._buddy_send(co, hid, trainer, live,
                                             step, feed, reset=True)
                    except HostLostError:
                        # WE were fenced mid-admission (e.g. our ship
                        # write outlasted a barrier timeout): the same
                        # stop-competing path as a fence during the
                        # window gather — the remaining survivors
                        # carry on without us
                        record_event("host_fenced", step=step)
                        got = self._rejoin_or_exit(hid, run_tag,
                                                   trainer, base_axes,
                                                   step)
                        if got is None:
                            return result()
                        step, rnd, restarts = got
                        known_live = sorted(co.live_hosts())
                        # same stop-competing pattern as the other
                        # fence handlers: restart the window loop on
                        # the adopted position instead of falling
                        # through to drain/drain checks computed from
                        # this round's now-stale verdicts
                        continue
                if self._drain_after:
                    # membership for the drain decision is the FROZEN
                    # round snapshot — a live co.live_hosts() query
                    # here could differ between hosts mid-tombstone
                    # and diverge the agreement
                    frozen_live = sorted(verdicts)
                    if since_drain is not None:
                        since_drain += 1
                    # PROACTIVE DRAIN: the rejoin barriers in reverse —
                    # agree the drain (same frozen verdicts on every
                    # host), fence at the boundary, shrink next window.
                    # The latch is straggler-AWARE: compute (v[3]),
                    # network (heartbeat-cadence lag) and data (agreed
                    # feed stream lag) signatures all count — see
                    # _drain_flags.
                    flags = self._drain_flags(verdicts, sdc=sdc)
                    for h in list(strag_counts):
                        if h not in flags:
                            strag_counts.pop(h)
                    for h, f in flags.items():
                        strag_counts[h] = strag_counts.get(h, 0) + 1 \
                            if f else 0
                    due = [h for h in frozen_live
                           if strag_counts.get(h, 0) >= self._drain_after]
                    # a straggler signature is ASYMMETRIC: when every
                    # live host latched (a systemic slowdown, or the
                    # collective wait inflating everyone's latency),
                    # there is no victim to drain — draining min(due)
                    # would fence a healthy host and cascade
                    asym = due and len(due) < len(frozen_live) \
                        and len(frozen_live) > 1
                    if asym and len(frozen_live) - 1 \
                            < self._drain_floor_hosts():
                        # capacity floor: below it a straggling pod is
                        # still a pod — stalling beats shrinking to
                        # nothing. Deterministic (frozen membership),
                        # so every host defers together.
                        record_event("drain_deferred", reason="floor",
                                     due=sorted(due), step=step)
                        asym = False
                        strag_counts.clear()
                    if asym and since_drain is not None \
                            and self._drain_cooldown \
                            and since_drain < self._drain_cooldown:
                        # rate limit: at most one host per cooldown
                        # windows — the post-shrink pod re-observes
                        # before a second victim is considered
                        record_event("drain_deferred",
                                     reason="cooldown",
                                     due=sorted(due), step=step)
                        asym = False
                    if asym:
                        drained = min(due)
                        # full hysteresis: EVERY count resets, so the
                        # post-shrink pod re-observes before it may
                        # drain again (never one host per window)
                        strag_counts.clear()
                        since_drain = 0
                        was_sdc = sdc is not None \
                            and drained in sdc.suspects()
                        if was_sdc:
                            # a re-admitted replacement starts with a
                            # clean record — the suspicion belonged to
                            # the drained incarnation's hardware
                            sdc.clear(drained)
                        record_event(
                            "elastic_drain", drained=drained, step=step,
                            capacity="%d/%d"
                            % (len(frozen_live) - 1,
                               self._coordinator.n_hosts),
                            windows=self._drain_after, sdc=was_sdc)
                        if drained == hid:
                            # a PLANNED loss: fence ourselves at the
                            # window boundary so the survivors' next
                            # gather shrinks immediately instead of
                            # stalling until this straggler becomes a
                            # CollectiveTimeoutError. The orchestrator
                            # restarts us; a healthy incarnation
                            # rejoins through the normal admission.
                            co.mark_lost(
                                hid, "drained: %s for "
                                "%d consecutive windows"
                                % ("suspected SDC host" if was_sdc
                                   else "critical straggler",
                                   self._drain_after))
                            record_event("host_exit", step=step)
                            return result()
                if feed is not None and feed.all_drained():
                    # decided from the agreed cursor map (identical on
                    # every live host after observe/rebalance), never
                    # from per-host views — all hosts break together
                    break
                continue
            # -- transient: pod-wide consensus rewind (parent semantics,
            #    restored straight onto the CURRENT — possibly shrunk —
            #    mesh). A PURE pp capacity loss (every survivor ok, the
            #    rewind only re-anchors the pod on the common
            #    checkpoint) is budget-free like the elastic shrink it
            #    replaces: no restart counted, no error backoff — a
            #    long-lived pp pod must survive arbitrarily many host
            #    losses, and only real FAULTS may exhaust the budget.
            #    Deterministic pod-wide: pp_rewind and the statuses are
            #    computed from the same frozen verdicts on every host.
            all_ok = all(v == "ok" for v in statuses.values())
            free_rewind = pp_rewind and all_ok
            # buddy generation this round can agree on: a COMMITTED
            # pp-loss round already advanced step (its buddy send was
            # skipped), so the mailboxes sit at the previous boundary;
            # an uncommitted fault round's mailboxes match this one
            bgen = step - w if all_ok else step
            breason = None
            if self._buddy:
                from . import buddy as buddy_mod
                breason = buddy_mod.agree_plan(
                    co, hid, "%sb%d" % (run_tag, rnd), live, lost,
                    sorted(set(live) | set(lost)), bgen,
                    p2p=self._buddy_p2p)
                if breason == "buddy_and_host_lost":
                    # the lost shard's warm replica died WITH it: real
                    # state is gone and the recovery is no longer the
                    # budget-free re-anchoring — this double failure
                    # charges the restart budget exactly once
                    free_rewind = False
            if not free_rewind:
                restarts += 1
                if restarts > self._max_restarts:
                    record_event("giveup", step=step, restarts=restarts)
                    raise RestartBudgetExceededError(
                        "pod restart budget (%d) exhausted at step %d; "
                        "last local error: %r" % (self._max_restarts,
                                                  step, err))
                delay = trainer._policy.delay_s(restarts - 1)
                record_event("pod_restart", step=step, restarts=restarts,
                             error=type(err).__name__ if err else None,
                             backoff_s=delay)
                trainer._policy.sleep(delay)
            # numeric_policy="rewind": agree the poison batch so every
            # host's replay skips it (lockstep gather — the free pp
            # rewind publishes an empty set like any healthy host)
            self._agree_poison(co, hid, run_tag, rnd, trainer, step,
                               err)
            if feed is not None and lost:
                # a shrink and a fault in the SAME window: re-home the
                # dead host's lanes first so the cursor restore (buddy
                # or disk) maps lane ownership onto the surviving set
                feed.rebalance(live, lags=self._agreed_lags(verdicts))
            # WARM path first: adopt the agreed buddy generation —
            # at most one window lost, no disk read. Any typed doubt
            # (breason) already fell the pod back below.
            got = self._buddy_restore(
                co, hid, run_tag, rnd, trainer, bgen, live, lost=lost,
                shardings=self._current_shardings(trainer), feed=feed,
                feed_lags=None if feed is None
                else self._agreed_lags(verdicts),
                agreed=True, reason=breason)
            from_disk = got is None
            if from_disk:
                from .. import io as io_mod
                report = io_mod.scrub_checkpoint(trainer._ckpt_dir)
                agreed_step = co.elect_restore_step(
                    hid, report["valid_steps"],
                    name="%se%d" % (run_tag, rnd))
                got = trainer._restore(
                    step=agreed_step,
                    shardings=self._current_shardings(trainer),
                    # the checkpoint's owner map may predate this
                    # window's membership — any orphan re-placement
                    # inside the cursor restore must use the AGREED
                    # lag snapshot, not each process's local gauges
                    feed_lags=None if feed is None
                    else self._agreed_lags(verdicts))
            # the restored scope carries the LR (and applied-factor
            # marker) from save time — reconcile with CURRENT capacity
            self._apply_lr_scale(trainer, live)
            if from_disk:
                # the disk rewind moved the pod below the mailbox
                # generations (and a poison replay may change the
                # trajectory): re-seed the buddy tier from the
                # restored state, reset= bypassing the rewind fence
                self._buddy_send(co, hid, trainer, live, got, feed,
                                 reset=True)
            record_event("pod_restore", step=got)
            step = got
        co.barrier(run_tag + "pod_end", hid)
        return result()

    def _rejoin_or_exit(self, hid, run_tag, trainer, base_axes, step):
        """Fenced-host tail: announce a rejoin and wait for admission.
        Returns the adopted (step, rnd, restarts) on success, None when
        this host stays out (rejoin disabled or not admitted in time —
        the survivors carry on either way)."""
        co = self._coordinator
        if not self._rejoin:
            record_event("host_exit", step=step)
            return None
        nonce = self._next_nonce(hid)
        try:
            co.announce_join(hid, nonce)
            record_event("join_announce", nonce=nonce, step=step)
            sync = co.join(hid, nonce, name=run_tag + "join")
        except CoordinationError as e:
            # not admitted (survivors finished, or a recovery storm):
            # stay out — a fenced host must never force its way back
            record_event("rejoin_failed", error=type(e).__name__,
                         nonce=nonce)
            return None
        new_step, new_rnd, new_restarts = sync
        try:
            live = sorted(co.live_hosts())
            self._retarget(trainer, base_axes, live, "elastic_grow",
                           joined=[hid], step=new_step)
            tag = "%s_h%d_n%d" % (run_tag, hid, nonce)
            co.barrier("ship" + tag, hid)
            co.barrier("shipped" + tag, hid)
            self._receive_state(hid, trainer, live, new_step)
            co.barrier("done" + tag, hid)
            # persist the adopted state: this host missed every
            # boundary saved while it was fenced, and the pod's
            # consensus election needs a step valid on ALL live hosts —
            # the sync step becomes that common point (survivors write
            # it too when it is not already a boundary they saved)
            trainer._save(new_step)
            # rejoin re-seed, mirroring the survivors' (they re-ring
            # over the grown membership at this same sync step): this
            # host's mailbox still holds its pre-death generation
            self._buddy_send(co, hid, trainer, live, new_step,
                             trainer._feed, reset=True)
        except HostLostError:
            # fenced AGAIN mid-admission (we were too slow to meet a
            # ship barrier): the survivors already moved on — stay out
            record_event("rejoin_failed", error="HostLostError",
                         nonce=nonce)
            return None
        record_event("rejoin", step=new_step, nonce=nonce)
        return int(new_step), int(new_rnd), int(new_restarts)
