"""Executor: run a Program on TPU as one fused XLA computation.

Reference parity: python/paddle/fluid/executor.py + framework/executor.cc.
The reference interprets the ProgramDesc op-by-op, dispatching device kernels.
TPU-native design: on first run of a (program, feed-signature) pair we trace
every op's JAX kernel into a single jax.jit'd step function

    step(state, feeds) -> (fetches, new_state)

where ``state`` is every persistable var (parameters, optimizer moments, LR
counters) resident in HBM. State buffers are DONATED, so XLA updates
parameters in place — zero-copy, the whole train step is one HLO module, and
XLA fuses across forward/backward/optimizer exactly like the reference's
fused ParallelExecutor graph, but compiler-driven.

Programs with no fetch_list (e.g. the startup program) run eagerly op-by-op —
initializers don't deserve a compile.
"""
import contextlib
import logging
import time
import warnings

import numpy as np
import jax
import jax.numpy as jnp

from . import faultinject
from . import obs
from . import resilience
from . import trace as trace_mod
from . import watchdog
from .dtypes import to_jax_dtype
from .place import CPUPlace, TPUPlace, _current_expected_place  # noqa: F401
from .program import Program, default_main_program
from .scope import global_scope
from ..ops.registry import get_op, has_op
from .trace import TraceContext, trace_block, GRAD_OP_TYPE, STEP_VAR

logger = logging.getLogger("paddle_tpu")


def _feed_signature(feed):
    # NB: use .dtype/.shape attributes — np.asarray on a jax.Array would
    # sync it to host, putting a D2H round-trip on every step.
    return tuple(sorted((k, tuple(v.shape), str(v.dtype))
                        for k, v in feed.items()))


def _want_vjp_set(program):
    """desc_ids of forward ops that some grad_of op in the program refers to."""
    want = set()
    for blk in program.blocks:
        for op in blk.ops:
            if op.type == GRAD_OP_TYPE:
                want.add(op.attrs["fwd_id"])
    return frozenset(want)


def _fetch_names(fetch_list):
    return [f.name if hasattr(f, "name") else f for f in fetch_list]


def _persistable_names(program):
    names = set()
    for blk in program.blocks:
        for v in blk.vars.values():
            if v.persistable:
                names.add(v.name)
    return names


def _uses_rng(program):
    for blk in program.blocks:
        for op in blk.ops:
            if op.type != GRAD_OP_TYPE and has_op(op.type) \
                    and get_op(op.type).uses_rng:
                return True
    return False


def _numeric_config(program, strategy):
    """Resolve (check_numerics, policy, skip_budget) for one run.

    A numeric_policy other than "raise" implies the finite guard even
    when check_numerics was left False — "skip"/"rewind" without the
    mask would be dead knobs."""
    policy, budget = "raise", 3
    if strategy is not None:
        bs = strategy._build_strategy
        policy = getattr(bs, "numeric_policy", "raise") or "raise"
        budget = int(getattr(bs, "numeric_skip_budget", 3) or 1)
    check = bool(
        getattr(program, "_check_numerics", False)
        or (strategy is not None and
            getattr(strategy._build_strategy, "check_numerics", False))
        or policy != "raise")
    return check, policy, budget


def _skip_guard(step):
    """numeric_policy="skip", the in-graph half: when ANY fetch/state
    var went non-finite this step, every state leaf (params, optimizer
    moments, PRNG counter) reverts to its pre-step value under one
    scalar select — the step simply never happened on-device. Works
    WITH buffer donation because the select runs inside the jitted
    computation; the host never has to resurrect a donated input."""
    def guarded(state_tuple, feed_tuple):
        fetches, new_state, finite = step(state_tuple, feed_tuple)
        ok = jnp.all(finite)
        new_state = tuple(jnp.where(ok, n, o)
                          for o, n in zip(state_tuple, new_state))
        return fetches, new_state, finite
    return guarded


def _first_offender(finite_row, fetch_names, state_names):
    """Name the first non-finite var from one per-var finite mask row
    (mask order: fetches, then carried state)."""
    finite_row = np.asarray(finite_row)
    if finite_row.ndim == 0:    # legacy scalar flag: no localization
        return None
    names = list(fetch_names) + list(state_names)
    idx = int(np.argmin(finite_row))
    return names[idx] if idx < len(names) else None


def _hit_step_feed(feed):
    """executor.step failpoint: lets a chaos schedule NaN-poison or
    bit-flip a named feed array (or raise/delay) at a chosen step."""
    out = faultinject.hit("executor.step", feed)
    return feed if out is faultinject.DROP else out


class Executor(object):
    def __init__(self, place=None):
        # Remember whether the caller chose the device. Only an EXPLICIT
        # place may pin jax.default_device during execution — a defaulted
        # Executor must respect an ambient jax.default_device(...) context
        # (e.g. the multichip dryrun pinning everything to CPU while a TPU
        # is attached); an unconditional inner pin would silently override
        # the caller's outer pin.
        self._explicit_place = place is not None
        self.place = place if place is not None else _current_expected_place()
        self._cache = {}
        # step-cache accounting (bench_micro's executor-cache-hit-rate
        # metric): a miss is a fresh trace+compile, a hit re-dispatches
        # the cached executable
        self.cache_hits = 0
        self.cache_misses = 0
        # numeric_policy="skip" accounting: CONSECUTIVE steps discarded
        # by the in-graph revert; any clean step resets it, crossing
        # the strategy's numeric_skip_budget escalates
        self._numeric_skips = 0

    def _device_ctx(self):
        """default_device context for execution: pin only when the user
        picked a place; otherwise defer to the ambient default."""
        if self._explicit_place:
            return jax.default_device(self.place.jax_device())
        return contextlib.nullcontext()

    def close(self):
        self._cache.clear()

    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           steps_per_dispatch=1):
        """Run the whole dataset through the jitted train step (reference
        executor.py train_from_dataset / MultiTrainer). The device_worker
        thread pool maps to background batch prefetch + JAX async
        dispatch: the host stages batch N+1 while the chip runs batch N.
        steps_per_dispatch=W batches W steps into one fused lax.scan
        device program (run_steps) — the reference's in-C++ trainer loop,
        recommended over remote/tunneled TPU links.
        Returns (steps_run, last_fetch_values)."""
        from ..trainer_factory import TrainerFactory
        if dataset is None:
            raise ValueError("dataset is required")
        program = program if program is not None else default_main_program()
        trainer_cls = TrainerFactory()._create_trainer(
            getattr(program, "_fleet_opt", None))
        trainer = trainer_cls(self, program)
        return trainer.run(dataset, fetch_list=fetch_list,
                           fetch_info=fetch_info,
                           print_period=print_period, debug=debug,
                           scope=scope,
                           steps_per_dispatch=steps_per_dispatch)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Same loop, but the program must be inference-only. The reference
        disables gradient push (python/paddle/fluid/executor.py:1061); a
        jitted step has no push to disable, so the equivalent safety is
        rejecting programs that would update parameters — otherwise
        "inference" on a training program silently trains."""
        program = program if program is not None else default_main_program()
        # lr_sched ops mutate persistable schedule counters — the same
        # "inference advances training state" trap clone(for_test=True)
        # strips them for (program.py clone).
        update_ops = sorted({
            op.type for blk in program.blocks for op in blk.ops
            if op.attrs.get("op_role") in ("optimize", "lr_sched")})
        if update_ops:
            raise ValueError(
                "infer_from_dataset got a program containing parameter-"
                "update ops %s; pass the inference program (e.g. "
                "program.clone(for_test=True) taken BEFORE minimize(), or "
                "use train_from_dataset to train)" % (update_ops,))
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period)

    # ------------------------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None, feed_var_name=None,
            fetch_var_name=None, scope=None, return_numpy=True,
            use_program_cache=True):
        from .compiler import CompiledProgram
        strategy = None
        if isinstance(program, CompiledProgram):
            strategy = program
            program = program._program
        if program is None:
            program = default_main_program()
        scope = scope if scope is not None else global_scope()
        feed = dict(feed or {})
        # started py_readers supply their own variables' batches
        # (reference create_py_reader_op: run-without-feed training loops);
        # an exhausted reader raises layers.io.EOFException here. Two-phase
        # so a sibling reader's EOF pushes already-dequeued batches back
        # (no lost data), and user-fed names are never overwritten.
        pulled = []
        try:
            for rdr in getattr(program, "_py_readers", ()):
                if rdr._started and any(n not in feed
                                        for n in rdr._names):
                    pulled.append((rdr, rdr._next_feed()))
        except Exception:
            for rdr, batch in pulled:
                rdr._push_back(batch)
            raise
        for rdr, batch in pulled:
            for n, v in batch.items():
                feed.setdefault(n, v)
        fetch_names = _fetch_names(fetch_list or [])

        if not fetch_names:
            self._run_eager(program, feed, scope)
            return []

        # chaos-harness injection point: one fire per jitted-step dispatch
        # (startup/eager programs don't count). A no-op unless a
        # FaultInjector is installed (resilience.inject / PADDLE_TPU_FAULTS).
        resilience.fire("step", what="Executor.run")
        feed = _hit_step_feed(feed)
        # straggler wiring: when detection is armed, the whole dispatch+
        # writeback (return_numpy syncs the fetches) is the step latency
        det_t0 = time.perf_counter() \
            if watchdog.straggler_detector() is not None else None

        if getattr(program, "_pp_plan", None) is not None:
            out = self._run_pipeline(program, feed, fetch_names, scope,
                                     return_numpy)
            if det_t0 is not None:
                watchdog.observe_step_latency(time.perf_counter() - det_t0,
                                              what="Executor.run")
            return out
        if strategy is not None and strategy._pp_enabled():
            out = self._run_compiled_pp(strategy, program, feed,
                                        fetch_names, scope, return_numpy)
            if det_t0 is not None:
                watchdog.observe_step_latency(time.perf_counter() - det_t0,
                                              what="Executor.run")
            return out

        # ---- the jitted single-step path ---------------------------------
        # phase spans (exec.step > compile/execute/writeback) + the
        # always-on executor_step_seconds{kind=} histograms — the obs
        # layer's executor leg
        with obs.span("exec.step", entry="run") as sp:
            out = self._run_jitted(program, feed, fetch_names, scope,
                                   return_numpy, use_program_cache,
                                   strategy, sp)
        if det_t0 is not None:
            watchdog.observe_step_latency(time.perf_counter() - det_t0,
                                          what="Executor.run")
        return out

    def _run_jitted(self, program, feed, fetch_names, scope,
                    return_numpy, use_program_cache, strategy, sp):
        t_total = time.perf_counter()
        state_names, uses_rng = self._prepare_state(program, feed, scope)
        feed_vals = self._convert_feed(program, feed)
        check_numerics, policy, skip_budget = _numeric_config(program,
                                                             strategy)
        key = (id(program), program._version, _feed_signature(feed_vals),
               tuple(fetch_names), tuple(state_names), check_numerics,
               None if strategy is None else strategy._cache_token())
        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            self.cache_misses += 1
            sp.set(cache="miss")
            t0 = time.perf_counter()
            with obs.span("exec.compile"):
                entry = self._compile(program, feed_vals, fetch_names,
                                      state_names, uses_rng, strategy,
                                      check_numerics, policy)
            resilience.observe_executor_step(
                "compile", time.perf_counter() - t0)
            if use_program_cache:
                self._cache[key] = entry
        else:
            self.cache_hits += 1
            sp.set(cache="hit")
        step_fn = entry

        state_vals = tuple(scope.find_var(n) for n in state_names)
        feed_tuple = tuple(feed_vals[k] for k in sorted(feed_vals))
        t0 = time.perf_counter()
        with obs.span("exec.execute"):
            if check_numerics:
                fetches, new_state, finite = step_fn(state_vals,
                                                     feed_tuple)
                finite = np.asarray(finite)
                if not finite.all():
                    self._numeric_fault(scope, state_names, new_state,
                                        finite, fetch_names, policy,
                                        skip_budget)
                elif policy == "skip":
                    self._numeric_skips = 0   # clean step ends a streak
            else:
                fetches, new_state = step_fn(state_vals, feed_tuple)
        resilience.observe_executor_step(
            "execute", time.perf_counter() - t0)
        t0 = time.perf_counter()
        with obs.span("exec.writeback"):
            out = self._writeback(scope, state_names, new_state, fetches,
                                  return_numpy)
        resilience.observe_executor_step(
            "writeback", time.perf_counter() - t0)
        resilience.observe_executor_step(
            "total", time.perf_counter() - t_total)
        return out

    @staticmethod
    def _writeback(scope, state_names, new_state, fetches, return_numpy):
        """Shared run()/run_steps() tail: persist the new state, convert
        fetches."""
        for n, v in zip(state_names, new_state):
            scope.set_var(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    @staticmethod
    def _state_step_no(state_names, new_state):
        """The program's PRNG step counter value, when it carries one —
        names the step in numeric_fault events."""
        try:
            i = state_names.index(STEP_VAR)
        except ValueError:
            return None
        return int(np.asarray(new_state[i]))

    def _numeric_fault(self, scope, state_names, new_state, finite_row,
                       fetch_names, policy, skip_budget,
                       window_offset=0):
        """One step went non-finite: localize the first offending var,
        record the numeric_fault event, and apply the policy tail.

        "skip": the in-graph guard already reverted the state — count
        the consecutive discard (SkipBudgetExceededError past the
        budget) and RETURN so the caller commits the reverted state.
        "rewind"/"raise": write the state back first (the inputs were
        donated, so leaving the scope pointing at them would poison
        every later run for callers that catch this to inspect/resume)
        and raise — NumericFaultError for the trainer's
        rewind-and-skip-the-batch recovery, today's plain
        FloatingPointError otherwise."""
        culprit = _first_offender(finite_row, fetch_names, state_names)
        step_no = self._state_step_no(state_names, new_state)
        evt = {"policy": policy}
        if culprit is not None:
            evt["culprit"] = culprit
        if step_no is not None:
            evt["step"] = step_no
        resilience.record_event("numeric_fault", **evt)
        where = "var %r" % culprit if culprit is not None \
            else "fetches or updated state"
        if policy == "skip":
            self._numeric_skips += 1
            if self._numeric_skips > skip_budget:
                self._writeback(scope, state_names, new_state, (),
                                False)
                raise resilience.SkipBudgetExceededError(
                    "numeric_policy='skip' discarded %d consecutive "
                    "steps (budget %d); last offender: %s — the fault "
                    "is persistent, not a poison batch"
                    % (self._numeric_skips, skip_budget, where),
                    step=step_no, culprit=culprit,
                    window_offset=window_offset)
            return
        self._writeback(scope, state_names, new_state, (), False)
        if policy == "rewind":
            raise resilience.NumericFaultError(
                "numeric fault: non-finite value (NaN/Inf) in %s of "
                "this step — rewinding to the last checkpoint with the "
                "poison batch skipped on replay" % where,
                step=step_no, culprit=culprit,
                window_offset=window_offset)
        raise FloatingPointError(
            "check_numerics: non-finite value (NaN/Inf) detected in "
            "%s of this step (reference parity: check_nan_inf)" % where)

    # ------------------------------------------------------------------
    def run_steps(self, program=None, feed=None, fetch_list=None,
                  scope=None, return_numpy=True, use_program_cache=True):
        """Run N consecutive steps as ONE device program (lax.scan).

        ``feed`` maps each feed name to an array with a leading steps
        axis: step i consumes ``feed[name][i]``. The traced step function
        is scanned over the stacked feeds with the persistable state as
        the carry, so parameters/optimizer moments/PRNG counter thread
        through on-device and the host dispatches ONE computation for the
        whole window. This is the reference's C++ trainer loop
        (`framework/trainer.cc` runs many steps without returning to
        Python) done the XLA way — and it takes per-step host/link
        latency (significant over remote TPU tunnels) off the critical
        path entirely.

        Returns the fetches of every step, stacked on a leading axis of
        length N. Per-step semantics (dropout PRNG folding, state
        updates) are identical to N sequential ``run`` calls — pinned by
        tests/test_executor_scan.py. Accepts a CompiledProgram: the scan
        is then jitted over the strategy's mesh with the same state/feed
        shardings as run() (stacked feeds gain a replicated steps axis).
        """
        from .compiler import CompiledProgram
        strategy = None
        if isinstance(program, CompiledProgram):
            # sharded window: same scan, jitted over the strategy's mesh
            strategy = program
            program = program._program
        if program is None:
            program = default_main_program()
        if any(r._started for r in getattr(program, "_py_readers", ())):
            raise ValueError("run_steps needs explicit stacked feeds, not "
                             "started py_readers")
        scope = scope if scope is not None else global_scope()
        feed = dict(feed or {})
        fetch_names = _fetch_names(fetch_list or [])
        if not feed or not fetch_names:
            raise ValueError("run_steps requires stacked feeds and a "
                             "fetch_list")
        # .shape/np.shape never sync a device array to host
        lens = {k: (np.shape(v)[0] if np.ndim(v) else None)
                for k, v in feed.items()}
        if None in lens.values() or len(set(lens.values())) != 1:
            raise ValueError(
                "every run_steps feed needs the same leading steps axis; "
                "got %r" % lens)
        n_steps = next(iter(lens.values()))
        if n_steps == 0:
            raise ValueError("run_steps needs at least one step; the "
                             "stacked feeds have a leading axis of 0")
        # one fire per scanned WINDOW (a window is one device dispatch —
        # the granularity at which a real preemption would kill the step)
        resilience.fire("step", what="Executor.run_steps")
        feed = _hit_step_feed(feed)
        # per-step straggler latency = window wall-clock / window length
        det_t0 = time.perf_counter() \
            if watchdog.straggler_detector() is not None else None

        def _observe(result):
            if det_t0 is not None:
                watchdog.observe_step_latency(
                    (time.perf_counter() - det_t0) / n_steps,
                    what="Executor.run_steps")
            return result
        if getattr(program, "_pp_plan", None) is not None:
            return _observe(self._run_pipeline_steps(
                program, feed, fetch_names, scope, return_numpy, n_steps))
        if strategy is not None and strategy._pp_enabled():
            return _observe(self._run_compiled_pp(
                strategy, program, feed, fetch_names, scope, return_numpy,
                windowed=True))
        # one exec.step parent per window — the run() path's grouping,
        # so the window's compile/execute/writeback phases share one
        # trace even when no ambient span is open around the caller
        with obs.span("exec.step", entry="run_steps",
                      steps=n_steps) as sp:
            return _observe(self._run_steps_jitted(
                program, strategy, feed, fetch_names, scope,
                return_numpy, use_program_cache, n_steps, sp))

    def _run_steps_jitted(self, program, strategy, feed, fetch_names,
                          scope, return_numpy, use_program_cache,
                          n_steps, sp):
        staged = self._convert_feed(program, feed, steps_axis=True)

        check_numerics, policy, skip_budget = _numeric_config(program,
                                                              strategy)
        state_names, uses_rng = self._prepare_state(program, staged, scope)
        key = (id(program), program._version,
               _feed_signature(staged), tuple(fetch_names),
               tuple(state_names), check_numerics, "scan",
               None if strategy is None else strategy._cache_token())
        t_total = time.perf_counter()
        fn = self._cache.get(key) if use_program_cache else None
        if fn is not None:
            self.cache_hits += 1
            sp.set(cache="hit")
        else:
            self.cache_misses += 1
            sp.set(cache="miss")
            t_compile = time.perf_counter()
            w_compile = obs.now()
            from .compiler import verify_for_compile
            verify_for_compile(
                program,
                None if strategy is None else strategy._build_strategy,
                feeds={k: tuple(np.shape(v)[1:])
                       for k, v in staged.items()},
                fetch_names=fetch_names, source="compile")
            base_step = self._make_step(program, sorted(staged),
                                        fetch_names, state_names, uses_rng,
                                        check_numerics)
            if check_numerics and policy == "skip":
                # revert inside each scan iteration: a poisoned step's
                # state never reaches the next step of the window
                base_step = _skip_guard(base_step)

            def multi(state_tuple, feed_stack_tuple):
                def body(carry, xs):
                    out = base_step(carry, xs)
                    # (fetches[, finite_flag]) stacked per step
                    return out[1], (out[0],) + out[2:]
                final_state, ys = jax.lax.scan(
                    body, state_tuple, feed_stack_tuple)
                return ys, final_state

            if strategy is not None:
                fn = strategy._build_multi_step(multi, state_names,
                                                sorted(staged))
            else:
                with warnings.catch_warnings():
                    warnings.simplefilter("ignore")  # CPU: no donation
                    jitted = jax.jit(multi, donate_argnums=(0,))

                def fn(state_vals, feed_tuple):
                    with self._device_ctx():
                        return jitted(state_vals, feed_tuple)
            if use_program_cache:
                self._cache[key] = fn
            resilience.observe_executor_step(
                "compile", time.perf_counter() - t_compile)
            obs.record("exec.compile", w_compile, obs.now())
        state_vals = tuple(scope.find_var(n) for n in state_names)
        feed_tuple = tuple(staged[k] for k in sorted(staged))
        t_exec = time.perf_counter()
        with obs.span("exec.execute"):
            ys, new_state = fn(state_vals, feed_tuple)
        resilience.observe_executor_step(
            "execute", time.perf_counter() - t_exec)
        if check_numerics:
            finite = np.asarray(ys[1])
            # per-step verdicts: (n_steps, n_vars) mask rows, or the
            # legacy (n_steps,) scalar flags
            step_ok = finite.all(axis=1) if finite.ndim == 2 else finite
            if not step_ok.all():
                k = int(np.argmax(~step_ok))
                if policy == "skip":
                    # each bad step's state already reverted in-graph
                    # inside the scan; account every discard, honoring
                    # a streak carried in from previous windows
                    streak, worst, last = self._numeric_skips, 0, None
                    for i, ok_i in enumerate(step_ok):
                        if ok_i:
                            streak = 0
                            continue
                        streak += 1
                        worst = max(worst, streak)
                        last = i
                        c = _first_offender(finite[i], fetch_names,
                                            state_names)
                        resilience.record_event(
                            "numeric_fault", policy="skip", step=i,
                            **({} if c is None else {"culprit": c}))
                    self._numeric_skips = streak
                    if worst > skip_budget:
                        self._writeback(scope, state_names, new_state,
                                        (), False)
                        raise resilience.SkipBudgetExceededError(
                            "numeric_policy='skip' discarded %d "
                            "consecutive steps (budget %d) inside one "
                            "run_steps window" % (worst, skip_budget),
                            step=last, window_offset=last)
                else:
                    # write the post-window state back first — the
                    # input buffers were donated, so leaving the scope
                    # pointing at them would poison every later run.
                    # Unlike run(), detection lands after the scanned
                    # window completes (a scan cannot abort mid-flight)
                    # — the step index still names the first offender
                    self._writeback(scope, state_names, new_state, (),
                                    False)
                    culprit = _first_offender(
                        finite[k] if finite.ndim == 2 else finite[k],
                        fetch_names, state_names)
                    resilience.record_event(
                        "numeric_fault", policy=policy, step=k,
                        **({} if culprit is None
                           else {"culprit": culprit}))
                    tail = "" if culprit is None \
                        else " (first offender: %r)" % culprit
                    if policy == "rewind":
                        raise resilience.NumericFaultError(
                            "numeric fault: non-finite value first "
                            "detected at step %d of this run_steps "
                            "window%s — rewinding with the poison "
                            "batch skipped on replay" % (k, tail),
                            step=k, culprit=culprit, window_offset=k)
                    raise FloatingPointError(
                        "check_numerics: non-finite value (NaN/Inf) "
                        "first detected at step %d of this run_steps "
                        "window%s" % (k, tail))
            elif policy == "skip":
                self._numeric_skips = 0
        t_wb = time.perf_counter()
        with obs.span("exec.writeback"):
            out = self._writeback(scope, state_names, new_state,
                                  ys[0], return_numpy)
        resilience.observe_executor_step(
            "writeback", time.perf_counter() - t_wb)
        resilience.observe_executor_step(
            "total", time.perf_counter() - t_total)
        return out

    # ------------------------------------------------------------------
    def _convert_feed(self, program, feed, steps_axis=False):
        """Host-side dtype normalization + ONE batched device_put for all
        feeds (a single transfer keeps per-array latency — significant over
        remote/tunneled TPU links — off the step critical path).
        steps_axis=True (run_steps): each array carries a leading steps
        axis; shape validation applies to the per-step remainder."""
        out = {}
        blk = program.global_block()
        for name, val in feed.items():
            if isinstance(val, jax.Array):   # already device-resident
                out[name] = val
                continue
            var = blk._find_var_recursive(name)
            dtype = np.dtype(jax.dtypes.canonicalize_dtype(
                to_jax_dtype(var.dtype))) if var is not None else None
            arr = np.asarray(val)
            if dtype is not None and arr.dtype != dtype:
                arr = arr.astype(dtype)
            if var is not None and var.shape is not None:
                want = var.shape
                got = arr.shape[1:] if steps_axis else arr.shape
                kind = "per-step " if steps_axis else ""
                if len(want) != len(got):
                    # named error at the feed boundary (reference parity:
                    # DataFeeder's check), instead of a jax shape error
                    # deep inside the trace
                    raise ValueError(
                        "feed %r has %srank %d (shape %s) but the program "
                        "declares rank %d (shape %s)"
                        % (name, kind, len(got), tuple(got), len(want),
                           tuple(want)))
                for w, g in zip(want, got):
                    if w not in (-1, g):
                        raise ValueError(
                            "feed %r %sshape %s incompatible with declared "
                            "%s" % (name, kind, got, want))
            out[name] = arr
        host = [k for k, v in out.items() if not isinstance(v, jax.Array)]
        if host:
            staged = jax.device_put([out[k] for k in host])
            out.update(zip(host, staged))
        return out

    def _prepare_state(self, program, feed, scope):
        """Select the persistable vars that form the step's carried state
        (+ the implicit PRNG step counter when the program uses RNG)."""
        persistable = _persistable_names(program)
        state_names = sorted(n for n in persistable
                             if scope.find_var(n) is not None
                             and n not in feed)
        uses_rng = _uses_rng(program)
        if uses_rng:
            if scope.find_var(STEP_VAR) is None:
                scope.set_var(STEP_VAR, jnp.asarray(0, jnp.int32))
            if STEP_VAR not in state_names:
                state_names.append(STEP_VAR)
        return state_names, uses_rng

    def _make_step(self, program, feed_names_sorted, fetch_names,
                   state_names, uses_rng, check_numerics=False):
        """Build THE pure step function: forward + backward + optimizer ops
        of `program` traced as one jax computation (what gets jitted)."""
        want_vjp = _want_vjp_set(program)
        seed = program.random_seed

        def step(state_tuple, feed_tuple):
            env = dict(zip(state_names, state_tuple))
            env.update(zip(feed_names_sorted, feed_tuple))
            if uses_rng:
                step_no = env.get(STEP_VAR, jnp.asarray(0, jnp.int32))
                base_key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                              step_no)
                env[STEP_VAR] = step_no + 1
            else:
                base_key = jax.random.PRNGKey(seed)
            ctx = TraceContext(program, base_key, want_vjp)
            trace_block(program.global_block(), env, ctx)
            fetches = tuple(
                trace_mod._lookup(env, n, _FetchOp) for n in fetch_names)
            new_state = tuple(env[n] for n in state_names)
            if check_numerics:
                # PER-VAR finite mask, index-aligned with fetch_names +
                # state_names so the host can NAME the first offender
                # (reference check_nan_inf names the op; we name the
                # tensor). Non-inexact vars hold a constant-folded True
                # placeholder purely to keep the indices aligned.
                flags = []
                for v in list(fetches) + list(new_state):
                    if jnp.issubdtype(jnp.result_type(v), jnp.inexact):
                        flags.append(jnp.all(jnp.isfinite(v)))
                    else:
                        flags.append(jnp.asarray(True))
                flag = jnp.stack(flags) if flags \
                    else jnp.ones((0,), jnp.bool_)
                return fetches, new_state, flag
            return fetches, new_state

        return step

    def _compile(self, program, feed_vals, fetch_names, state_names,
                 uses_rng, strategy, check_numerics=False,
                 numeric_policy="raise"):
        # Program verification at the compile seam (one walk per cache
        # miss): located diagnostics BEFORE the trace turns a malformed
        # program into a first-named-error or a jax traceback
        from .compiler import verify_for_compile
        verify_for_compile(
            program,
            None if strategy is None else strategy._build_strategy,
            feeds={k: np.shape(v) for k, v in feed_vals.items()},
            fetch_names=fetch_names, source="compile")
        step = self._make_step(program, sorted(feed_vals), fetch_names,
                               state_names, uses_rng, check_numerics)
        if check_numerics and numeric_policy == "skip":
            # wrap BEFORE any strategy lowering so the revert select is
            # part of the (globally-viewed) jitted computation
            step = _skip_guard(step)
        if strategy is not None:
            return strategy._build_step(self, step, program, state_names,
                                        sorted(feed_vals), feed_vals,
                                        check_numerics)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # CPU ignores donation; fine.
            jitted = jax.jit(step, donate_argnums=(0,))

        def run_step(state_vals, feed_tuple):
            with self._device_ctx():
                return jitted(state_vals, feed_tuple)
        return run_step

    # ------------------------------------------------------------------
    def _pipeline_build(self, program, fetch_names, windowed=False):
        """Build (or fetch the program-cached) fused pipeline step.

        Returns (plan, init_fn, fn) where fn is jitted:
          windowed=False: fn(params, opt_state, x_micro, ys_micro,
              ys_full) -> (fetch_tuple, params, opt_state)
          windowed=True:  same signature with a leading steps axis on the
              data args, scanned on-device (run_steps for pipelines).

        Fetches may be the loss (from the schedule) and/or any var the
        unstamped loss section computes — those are evaluated by one
        extra pipeline forward + the traced tail on the UN-microbatched
        batch with the PRE-update params, which is exactly what a serial
        Executor.run of the unpartitioned program fetches."""
        from ..distributed import pipeline_program as ppp
        from ..distributed.pipeline import (pipeline_loss_and_grads,
                                            pipeline_1f1b_step,
                                            pipeline_forward)
        from ..distributed.mesh import get_mesh
        plan = program._pp_plan
        mesh = get_mesh()
        if mesh is None or "pp" not in mesh.axis_names:
            raise ValueError(
                "pipeline program needs an installed mesh with a 'pp' "
                "axis — call fleet.init with mesh_axes containing 'pp'")
        if mesh.shape["pp"] != plan.n_stage:
            raise ValueError(
                "program has %d pipeline stages but the mesh 'pp' axis has "
                "%d devices — they must match" % (plan.n_stage,
                                                  mesh.shape["pp"]))
        tail_produced = set()
        for op in plan.tail_ops:
            tail_produced.update(op.output_names())
        aux_names = [n for n in fetch_names if n != plan.loss_name]
        unknown = [n for n in aux_names if n not in tail_produced]
        if unknown:
            raise ValueError(
                "pipeline fetch_list entries must be the loss or vars "
                "computed by the unstamped loss section; %r are not "
                "(stage outputs stay sharded on the pp ring)" % (unknown,))
        init_fn, update_fn = ppp.make_update_fn(program._pp_optimizer)
        dp_axis = "dp" if ("dp" in mesh.axis_names and
                           mesh.shape["dp"] > 1) else None
        step_key = (plan.schedule, mesh, dp_axis, tuple(fetch_names),
                    windowed, type(program._pp_optimizer).__name__)
        cache = getattr(program, "_pp_step_cache", None)
        if cache is None:
            cache = program._pp_step_cache = {}
        fn = cache.get(step_key)
        if fn is None:
            stage_fn = ppp.make_stage_fn(program, plan)
            loss_fn = ppp.make_loss_fn(program, plan)
            tail_fn = ppp.make_tail_fn(program, plan, aux_names) \
                if aux_names else None
            if plan.schedule == "gpipe":
                def pipeline_call(params, x, ys):
                    def global_loss(out, ym):
                        return jnp.mean(jax.vmap(loss_fn)(out, ym))
                    return pipeline_loss_and_grads(
                        stage_fn, global_loss, params, x, ys, mesh,
                        dp_axis=dp_axis)
            elif plan.schedule == "1f1b":
                def pipeline_call(params, x, ys):
                    return pipeline_1f1b_step(stage_fn, loss_fn, params,
                                              x, ys, mesh, dp_axis=dp_axis)
            else:
                raise ValueError("unknown pp_schedule %r" % plan.schedule)

            def _unmicro(a):
                # microbatch() is a plain reshape, so merging the first
                # two dims recovers the original batch order
                return a.reshape((a.shape[0] * a.shape[1],) + a.shape[2:])

            def _step(params, opt_state, x, ys):
                loss, grads = pipeline_call(params, x, ys)
                aux = ()
                if tail_fn is not None:
                    h = pipeline_forward(stage_fn, params, x, mesh,
                                         dp_axis=dp_axis)
                    aux = tail_fn(_unmicro(h),
                                  tuple(_unmicro(y) for y in ys))
                params, opt_state = update_fn(params, grads, opt_state)
                fetches = tuple(
                    loss if n == plan.loss_name
                    else aux[aux_names.index(n)] for n in fetch_names)
                return fetches, params, opt_state

            if windowed:
                def _multi(params, opt_state, xs, yss):
                    def body(carry, data):
                        p, s = carry
                        fetches, p, s = _step(p, s, *data)
                        return (p, s), fetches
                    (params, opt_state), stacked = jax.lax.scan(
                        body, (params, opt_state), (xs, yss))
                    return stacked, params, opt_state
                target = _multi
            else:
                target = _step
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")  # CPU ignores donation
                fn = jax.jit(target, donate_argnums=(0, 1))
            cache[step_key] = fn
        return plan, init_fn, fn

    def _run_pipeline(self, program, feed, fetch_names, scope,
                      return_numpy):
        """Execute a fleet-partitioned pipeline Program: one jitted step =
        GPipe/1F1B schedule over the mesh's pp axis (x dp when present) +
        the inner optimizer's functional update on the stacked stage
        params (distributed/pipeline_program.py)."""
        from ..distributed import pipeline_program as ppp
        plan, init_fn, step = self._pipeline_build(program,
                                                   tuple(fetch_names))
        params = ppp.stack_params_from_scope(plan, scope)
        opt_state = getattr(program, "_pp_opt_state", None)
        if opt_state is None:
            opt_state = init_fn(params)
        feed_vals = self._convert_feed(program, feed)
        x = ppp.microbatch(feed_vals[plan.x_feed], plan.n_micro)
        ys = tuple(ppp.microbatch(feed_vals[n], plan.n_micro)
                   for n in plan.y_feeds)
        fetches, params, opt_state = step(params, opt_state, x, ys)
        ppp.unstack_params_to_scope(plan, scope, params)
        program._pp_opt_state = opt_state
        if getattr(program, "_check_numerics", False):
            # parity with run(): a non-finite fetch raises instead of
            # silently training on
            for name, arr in zip(fetch_names, fetches):
                if not np.isfinite(np.asarray(arr)).all():
                    raise FloatingPointError(
                        "non-finite value in pipeline fetch %r" % (name,))
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    def _run_pipeline_steps(self, program, feed, fetch_names, scope,
                            return_numpy, n_steps):
        """run_steps for pipeline programs: the whole W-step window is
        ONE device program — lax.scan over the fused GPipe/1F1B step with
        (params, opt_state) as carry."""
        from ..distributed import pipeline_program as ppp
        plan, init_fn, fn = self._pipeline_build(program,
                                                 tuple(fetch_names),
                                                 windowed=True)
        params = ppp.stack_params_from_scope(plan, scope)
        opt_state = getattr(program, "_pp_opt_state", None)
        if opt_state is None:
            opt_state = init_fn(params)
        feed_vals = self._convert_feed(program, feed, steps_axis=True)

        def micro_steps(name):
            arr = jnp.asarray(feed_vals[name])
            if arr.shape[1] % plan.n_micro:
                raise ValueError(
                    "per-step batch %d not divisible by n_micro %d"
                    % (arr.shape[1], plan.n_micro))
            return arr.reshape((arr.shape[0], plan.n_micro,
                                arr.shape[1] // plan.n_micro)
                               + arr.shape[2:])

        xs = micro_steps(plan.x_feed)
        yss = tuple(micro_steps(n) for n in plan.y_feeds)
        stacked, params, opt_state = fn(params, opt_state, xs, yss)
        ppp.unstack_params_to_scope(plan, scope, params)
        program._pp_opt_state = opt_state
        if getattr(program, "_check_numerics", False):
            # the scan cannot abort mid-window; detect afterwards and
            # name the first offending step (loss is always fetched or
            # fetchable — check every fetched output)
            for name, arr in zip(fetch_names, stacked):
                bad = ~np.isfinite(np.asarray(arr))
                if bad.any():
                    step_idx = int(np.argwhere(
                        bad.reshape(bad.shape[0], -1).any(1))[0][0])
                    raise FloatingPointError(
                        "non-finite value in pipeline run_steps fetch %r "
                        "at window step %d" % (name, step_idx))
        if return_numpy:
            return [np.asarray(f) for f in stacked]
        return list(stacked)

    # ------------------------------------------------------------------
    def _run_compiled_pp(self, strategy, program, feed, fetch_names,
                         scope, return_numpy, windowed=False):
        """CompiledProgram pipeline path (BuildStrategy.pp_stages / a >1
        "pp" mesh axis): the strategy's CompilePlan cuts the minimized
        program (trace -> cut -> schedule -> jit) and the step lowers
        through the GPipe/1F1B schedule inside one shard_map over the
        pp x dp mesh — dp gradient sync (quantized included) and the
        program's own update section run unchanged on the other axes.
        Scope stays in per-stage var names (checkpoints/elastic
        machinery see the usual layout); state is stacked onto the pp
        axis per dispatch and unstacked on the way out."""
        from ..distributed import pipeline_program as ppp
        feed_vals = self._convert_feed(program, feed, steps_axis=windowed)
        # verify WITH the real feed shapes + fetch roots before the cut:
        # feed-dependent pp checks (micro-batch divisibility, dp batch
        # divisibility, dead ops) must fire on the actual pp seam, not
        # only in compile_plan's feed-less guard
        from .compiler import verify_for_compile
        verify_for_compile(
            program, strategy._build_strategy,
            feeds={k: (tuple(np.shape(v)[1:]) if windowed
                       else tuple(np.shape(v)))
                   for k, v in feed_vals.items()},
            fetch_names=fetch_names, source="compile")
        cplan = strategy.compile_plan()
        cut = cplan.cut
        plan = cut.plan
        expect = set([plan.x_feed] + list(plan.y_feeds))
        if set(feed_vals) != expect:
            raise ValueError(
                "pipeline program expects exactly the feeds %r; got %r"
                % (sorted(expect), sorted(feed_vals)))
        check_numerics = bool(
            getattr(program, "_check_numerics", False) or
            getattr(strategy._build_strategy, "check_numerics", False))

        def _micro(name):
            arr = jnp.asarray(feed_vals[name])
            if not windowed:
                return ppp.microbatch(arr, plan.n_micro)
            if arr.shape[1] % plan.n_micro:
                raise ValueError(
                    "per-step batch %d not divisible by pp_micro_batches "
                    "%d" % (arr.shape[1], plan.n_micro))
            return arr.reshape((arr.shape[0], plan.n_micro,
                                arr.shape[1] // plan.n_micro)
                               + arr.shape[2:])

        feed_order = [plan.x_feed] + list(plan.y_feeds)
        micro = {n: _micro(n) for n in feed_order}
        key = (id(program), program._version,
               tuple((n, tuple(micro[n].shape), str(micro[n].dtype))
                     for n in feed_order),
               tuple(fetch_names), check_numerics,
               "pp_scan" if windowed else "pp", cplan.token)
        entry = self._cache.get(key)
        if entry is None:
            self.cache_misses += 1
            entry = strategy._build_pp_step(
                program, cplan, tuple(fetch_names),
                {n: tuple(micro[n].shape) for n in feed_order},
                check_numerics, windowed)
            self._cache[key] = entry
        else:
            self.cache_hits += 1
        (stacked_names, stage_cols, shared_names, forder), step_fn = entry

        # flat state order = the step's external signature: per-stage
        # vars grouped by template (stage-major within), then shared.
        # Plain replicated scope arrays in, plain arrays out — the
        # pp-stacking happens INSIDE the jit (no eager multi-device op
        # may race another host thread's dispatch)
        flat_names = [nm for t in stacked_names for nm in stage_cols[t]]
        flat_names += list(shared_names)
        state_vals = []
        for nm in flat_names:
            v = scope.find_var(nm)
            if v is None:
                raise ValueError(
                    "pipeline state %r not initialized — run the "
                    "startup program first" % nm)
            state_vals.append(v)
        feed_tuple = tuple(micro[n] for n in forder)
        out = step_fn(tuple(state_vals), feed_tuple)

        def _writeback_pp(new_state):
            for nm, v in zip(flat_names, new_state):
                scope.set_var(nm, v)

        if windowed:
            ys, new_state = out
            fetch_out = ys[0]
            if check_numerics:
                finite = np.asarray(ys[1])
                if not finite.all():
                    # state back first: inputs were donated (run() parity)
                    _writeback_pp(new_state)
                    raise FloatingPointError(
                        "check_numerics: non-finite value (NaN/Inf) first "
                        "detected at step %d of this pipeline run_steps "
                        "window" % int(np.argmin(finite)))
        elif check_numerics:
            fetch_out, new_state, finite = out
            if not bool(np.asarray(finite)):
                _writeback_pp(new_state)
                raise FloatingPointError(
                    "check_numerics: non-finite value (NaN/Inf) detected "
                    "in fetches or updated state of this pipeline step")
        else:
            fetch_out, new_state = out
        _writeback_pp(new_state)
        if return_numpy:
            return [np.asarray(f) for f in fetch_out]
        return list(fetch_out)

    # ------------------------------------------------------------------
    def dump_hlo(self, program=None, feed=None, fetch_list=None,
                 scope=None, include_compiled=True):
        """Return the XLA text of the SINGLE jitted step for (program,
        feed, fetch_list): {"lowered": StableHLO, "compiled": optimized
        HLO}.

        The TPU-native debugger (ref python/paddle/fluid/debugger.py
        pprint_program / graphviz): one module containing forward, backward
        and optimizer ops — the fused-step design stated in SURVEY §1 —
        inspectable as text. Run the startup program first so parameters
        exist in the scope. Accepts a CompiledProgram too, in which case
        the module is lowered with the strategy's mesh shardings (the dump
        then shows the partitioned program with its collectives).
        """
        from .compiler import CompiledProgram
        strategy = None
        if isinstance(program, CompiledProgram):
            strategy = program
            program = program._program
        if program is None:
            program = default_main_program()
        scope = scope if scope is not None else global_scope()
        feed = dict(feed or {})
        fetch_names = _fetch_names(fetch_list or [])
        state_names, uses_rng = self._prepare_state(program, feed, scope)
        feed_vals = self._convert_feed(program, feed)
        step = self._make_step(program, sorted(feed_vals), fetch_names,
                               state_names, uses_rng)
        state_vals = tuple(scope.find_var(n) for n in state_names)
        feed_tuple = tuple(feed_vals[k] for k in sorted(feed_vals))
        if strategy is not None:
            mesh = strategy._mesh_obj()
            state_sh = tuple(strategy._var_sharding(n, mesh)
                             for n in state_names)
            feed_sh = tuple(strategy._feed_sharding(n, mesh)
                            for n in sorted(feed_vals))
            jitted = jax.jit(step, in_shardings=(state_sh, feed_sh),
                             out_shardings=(None, state_sh),
                             donate_argnums=(0,))
            with mesh:
                lowered = jitted.lower(state_vals, feed_tuple)
                out = {"lowered": lowered.as_text()}
                if include_compiled:
                    out["compiled"] = lowered.compile().as_text()
            return out
        with self._device_ctx():
            lowered = jax.jit(step, donate_argnums=(0,)).lower(
                state_vals, feed_tuple)
            out = {"lowered": lowered.as_text()}
            if include_compiled:
                out["compiled"] = lowered.compile().as_text()
        return out

    # ------------------------------------------------------------------
    def _run_eager(self, program, feed, scope):
        """Op-by-op eager execution (startup programs, init ops)."""
        env = {}
        persistable = _persistable_names(program)
        for n in persistable:
            v = scope.find_var(n)
            if v is not None:
                env[n] = v
        env.update(self._convert_feed(program, feed))
        salt = scope.find_var("@EAGER_SALT@") or 0
        scope.set_var("@EAGER_SALT@", salt + 1)
        base_key = jax.random.fold_in(
            jax.random.PRNGKey(program.random_seed), salt)
        ctx = TraceContext(program, base_key, _want_vjp_set(program))
        with self._device_ctx():
            trace_block(program.global_block(), env, ctx)
        for n in persistable:
            if n in env:
                scope.set_var(n, env[n])


class _FetchOp(object):
    type = "fetch"
