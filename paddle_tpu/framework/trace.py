"""Trace engine: turn a Program into one JAX computation.

Reference parity: paddle/fluid/framework/executor.cc op loop +
grad_op_desc_maker.h. Instead of dispatching per-op kernels at runtime, we
*trace* every op's JAX kernel once under jax.jit, producing a single fused XLA
HLO computation for the whole program (forward + backward + optimizer). This
is the TPU-native realization of the reference ParallelExecutor's fused-graph
goal (framework/details/build_strategy.cc).

Autodiff: backward.append_backward emits generic ``grad_of`` ops. When the
forward op is traced we also capture its jax.vjp; the paired grad op later
calls that vjp, so the forward subgraph is computed ONCE and residuals are
shared — same cost model as the reference's explicit grad kernels.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..ops.registry import get_op
from .program import Program  # noqa: F401  (for type reference)

EMPTY_VAR = "@EMPTY@"
STEP_VAR = "@STEP_COUNTER@"
GRAD_OP_TYPE = "grad_of"


def zero_cotangent(v):
    if jnp.issubdtype(jnp.result_type(v), jnp.inexact):
        return jnp.zeros_like(v)
    return np.zeros(np.shape(v), dtype=jax.dtypes.float0)


class _VjpRecord(object):
    __slots__ = ("vjp_fn", "outs", "in_slots")

    def __init__(self, vjp_fn, outs, in_slots):
        self.vjp_fn = vjp_fn
        self.outs = outs          # {slot: [arrays]} forward outputs
        self.in_slots = in_slots  # [(slot, idx)] aligned with vjp grads


class TraceContext(object):
    """Per-trace state: PRNG derivation, vjp pairing, program access."""

    def __init__(self, program, base_key, want_vjp=frozenset()):
        self.program = program
        self.base_key = base_key
        self.want_vjp = want_vjp
        self.vjp_cache = {}
        self._op_key = base_key
        self._op_rng_count = 0
        self.outer_env = None  # set while tracing a uses_subblock op
        # quantized data-parallel gradient sync: when the compiler traces
        # the step inside a shard_map with quantize_collectives on, every
        # parameter gradient is synced (quantize -> psum -> dequantize)
        # the moment it is produced — see _maybe_sync_param_grads. The
        # scope also binds the sync axis so program-level collective ops
        # (c_allreduce_*) are live inside the quantized step.
        from ..ops import collective_ops as _cops
        self.grad_sync = _cops.current_grad_sync()
        self.synced_grads = set()
        self.bound_axes = () if self.grad_sync is None \
            else (self.grad_sync.axis_name,)
        # once-per-k quantized sync for grad-merge windows: when the
        # sync context opts in (BuildStrategy.quantize_merge_sync) and
        # the program carries GradientMergeOptimizer structure, the raw
        # per-step grads accumulate LOCALLY (exact fp32) and the sync
        # moves to the gated merged gradient under lax.cond — see
        # _maybe_sync_param_grads / _detect_merge_plan
        if self.grad_sync is not None and \
                getattr(self.grad_sync, "merge_window", False):
            self.merge_deferred, self.merge_gated = \
                _detect_merge_plan(program)
        else:
            self.merge_deferred, self.merge_gated = frozenset(), {}

    def begin_op(self, rng_tag):
        """rng_tag is the op's structural position (block, index) hash —
        stable across program rebuilds, unlike the global desc_id."""
        self._op_key = jax.random.fold_in(self.base_key, rng_tag % (2**31))
        self._op_rng_count = 0

    def rng(self):
        """Deterministic per-op PRNG key; stable across shardings/devices."""
        k = jax.random.fold_in(self._op_key, self._op_rng_count)
        self._op_rng_count += 1
        return k

    def trace_block(self, block, env):
        trace_block(block, env, self)


def _lookup(env, name, op):
    try:
        return env[name]
    except KeyError:
        raise KeyError(
            "op {%s} needs input var %r which has no value; it was neither "
            "fed, nor in scope, nor produced by an earlier op" % (op.type, name))


def _gather_inputs(op, env):
    return {slot: [_lookup(env, n, op) for n in names if n != EMPTY_VAR]
            for slot, names in op.inputs.items()}


def _bind_outputs(op, outs, env):
    for slot, names in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        if len(vals) != len(names):
            raise RuntimeError(
                "op {%s} slot %r produced %d values for %d vars" %
                (op.type, slot, len(vals), len(names)))
        for name, val in zip(names, vals):
            if name != EMPTY_VAR:
                env[name] = val


def _rng_tag(block, idx):
    return (block.idx + 1) * 1000003 + idx


GRAD_SUFFIX = "@GRAD"


def _detect_merge_plan(program):
    """Find GradientMergeOptimizer structure per persistable param:

        g = w@GRAD
        acc_new    = elementwise_add(acc, g)        # acc: *.grad_acc*
        apply_grad = scale(acc_new, 1/k)
        gated      = where(is_apply, apply_grad, zeros)
        <optimizer op consumes gated as Grad>

    Returns (deferred, gated): ``deferred`` is the raw grad names whose
    every-step sync is skipped; ``gated`` maps the where-output name ->
    {"raw": raw grad name, "pred": is_apply var name, "k": merge factor
    or None}. Cached per (program, version) — attrs-only stamping does
    not invalidate it, but minimize()/append_op bump the version."""
    cached = getattr(program, "_merge_plan_cache", None)
    if cached is not None and cached[0] == program._version:
        return cached[1], cached[2]
    blk = program.global_block()
    producer = {}
    for op in blk.ops:
        for nm in op.output_names():
            producer[nm] = op
    deferred, gated = set(), {}
    for op in blk.ops:
        if op.attrs.get("op_role") != "optimize" or "Grad" not in op.inputs \
                or "Param" not in op.inputs:
            continue
        pname = op.inputs["Param"][0]
        gname = op.inputs["Grad"][0]
        raw = pname + GRAD_SUFFIX
        if gname == raw:
            continue
        where_op = producer.get(gname)
        if where_op is None or where_op.type != "where":
            continue
        scale_op = None
        for slot in ("X", "Y"):
            cand = producer.get(where_op.inputs.get(slot, [""])[0])
            if cand is not None and cand.type == "scale":
                scale_op = cand
                break
        if scale_op is None:
            continue
        add_op = producer.get(scale_op.inputs["X"][0])
        if add_op is None or add_op.type != "elementwise_add":
            continue
        add_ins = add_op.input_names()
        if raw not in add_ins:
            continue
        acc = next((n for n in add_ins if n != raw), None)
        acc_var = blk._find_var_recursive(acc) if acc else None
        if acc_var is None or not getattr(acc_var, "persistable", False) \
                or ".grad_acc" not in acc:
            continue
        s = float(scale_op.attrs.get("scale", 1.0))
        k = None
        if 0.0 < s < 1.0 and abs(1.0 / s - round(1.0 / s)) < 1e-6:
            k = int(round(1.0 / s))
        deferred.add(raw)
        gated[gname] = {"raw": raw,
                        "pred": where_op.inputs["Condition"][0], "k": k}
    out = (frozenset(deferred), gated)
    program._merge_plan_cache = (program._version,) + out
    return out


def _maybe_sync_param_grads(op, env, ctx):
    """Quantized data-parallel gradient sync (ctx.grad_sync, installed by
    CompiledProgram under BuildStrategy.quantize_collectives).

    Fires on the FINAL binding of a persistable var's gradient — either
    the grad op binding ``w@GRAD`` directly, or the ``sum`` op merging
    ``w@GRAD@RENAME@k`` contributions — and replaces it in env with the
    synced value. Every consumer (grad clip, regularizer, gradient-merge
    accumulation, optimizer) then sees the globally-synced gradient,
    matching pjit's implicit-psum semantics; gradient-merge buffers
    accumulate the already-synced fp32 value, so accumulation stays
    exact and only the cross-host sync is quantized. Once per grad name
    per trace (ctx.synced_grads)."""
    sync = ctx.grad_sync
    if sync is None:
        return
    blk = ctx.program.global_block()
    for names in op.outputs.values():
        for n in names:
            if n in ctx.synced_grads or n not in env:
                continue
            spec = ctx.merge_gated.get(n)
            if spec is not None and spec["pred"] in env:
                # merge BOUNDARY: the gated merged gradient syncs under
                # lax.cond on the program's own apply predicate — the
                # k-1 non-apply steps skip the collective entirely
                ctx.synced_grads.add(n)
                env[n] = sync.sync_merged(spec["raw"], env[n],
                                          env[spec["pred"]], spec["k"])
                continue
            if not n.endswith(GRAD_SUFFIX):
                continue
            var = blk._find_var_recursive(n[:-len(GRAD_SUFFIX)])
            if var is None or not getattr(var, "persistable", False):
                continue
            if n in ctx.merge_deferred:
                # raw per-step grad of a merged param: accumulate
                # LOCALLY (exact fp32), sync once at the boundary above
                ctx.synced_grads.add(n)
                continue
            ctx.synced_grads.add(n)
            env[n] = sync.sync(n, env[n])


def trace_block(block, env, ctx):
    for i, op in enumerate(block.ops):
        trace_op(op, env, ctx, _rng_tag(block, i))


def trace_op(op, env, ctx, rng_tag=0):
    if op.type == GRAD_OP_TYPE:
        return _trace_grad_op(op, env, ctx)

    opdef = get_op(op.type)
    ins = _gather_inputs(op, env)
    ctx.begin_op(rng_tag)

    prev_outer = ctx.outer_env
    if opdef.uses_subblock:
        ctx.outer_env = env
    try:
        if op.desc_id in ctx.want_vjp and opdef.differentiable:
            outs = _trace_with_vjp(op, opdef, ins, ctx, rng_tag=rng_tag)
        else:
            outs = opdef.fn(ctx, ins, op.attrs)
    finally:
        ctx.outer_env = prev_outer
    _bind_outputs(op, outs, env)
    _maybe_sync_param_grads(op, env, ctx)


def _split_diff(opdef, ins):
    """Partition inputs into differentiable (flat list) and closed-over."""
    flat, slots = [], []
    for slot in sorted(ins):
        if slot in opdef.nondiff:
            continue
        for i, v in enumerate(ins[slot]):
            flat.append(v)
            slots.append((slot, i))
    return flat, slots


def _trace_with_vjp(op, opdef, ins, ctx, desc_id=None, rng_tag=0):
    desc_id = op.desc_id if desc_id is None else desc_id
    flat, in_slots = _split_diff(opdef, ins)

    def pure(*flat_vals):
        ins2 = {s: list(vs) for s, vs in ins.items()}
        for (slot, i), v in zip(in_slots, flat_vals):
            ins2[slot][i] = v
        ctx.begin_op(rng_tag)  # reset rng so replays are identical
        outs = opdef.fn(ctx, ins2, op.attrs)
        return {s: (list(v) if isinstance(v, (list, tuple)) else [v])
                for s, v in outs.items()}

    outs, vjp_fn = jax.vjp(pure, *flat)
    ctx.vjp_cache[desc_id] = _VjpRecord(vjp_fn, outs, in_slots)
    return outs


def _trace_grad_op(op, env, ctx):
    fwd_id = op.attrs["fwd_id"]
    rec = ctx.vjp_cache.get(fwd_id)
    if rec is None:
        # Forward op is not in this program (e.g. a pruned/partial program):
        # recompute its vjp from the forward inputs the grad op carries.
        # Inside one jitted train step this never happens — the pairing above
        # shares residuals, matching the reference's fwd/bwd kernel split.
        opdef = get_op(op.attrs["fwd_type"])
        fwd_ins = {slot[len("X:"):]: [_lookup(env, n, op) for n in names]
                   for slot, names in op.inputs.items()
                   if slot.startswith("X:")}
        fwd_op_attrs = op.attrs.get("fwd_attrs", {})

        class _FwdProxy(object):
            attrs = fwd_op_attrs
            type = op.attrs["fwd_type"]
            desc_id = fwd_id
        _trace_with_vjp(_FwdProxy, opdef, fwd_ins, ctx, desc_id=fwd_id)
        rec = ctx.vjp_cache[fwd_id]

    # Build cotangents matching the forward output structure.
    cot = {}
    for slot, fwd_vals in rec.outs.items():
        og_names = op.inputs.get("OG:" + slot, [EMPTY_VAR] * len(fwd_vals))
        cot[slot] = [env[n] if (n != EMPTY_VAR and n in env)
                     else zero_cotangent(v)
                     for n, v in zip(og_names, fwd_vals)]
    grads = rec.vjp_fn(cot)

    outs = {}
    for (slot, i), g in zip(rec.in_slots, grads):
        names = op.outputs.get("IG:" + slot)
        if not names or i >= len(names) or names[i] == EMPTY_VAR:
            continue
        if g is None or (hasattr(g, "dtype") and g.dtype == jax.dtypes.float0):
            continue
        outs.setdefault("IG:" + slot, {})[i] = g
    # normalize to aligned lists
    result = {}
    for slot, names in op.outputs.items():
        if slot not in outs:
            continue
        vals = [outs[slot].get(i, None) for i in range(len(names))]
        # drop positions with no grad by marking EMPTY binding
        result[slot] = [v if v is not None else None for v in vals]
        for i, v in enumerate(vals):
            if v is None and names[i] != EMPTY_VAR:
                raise RuntimeError(
                    "grad_of(%s): no gradient produced for %r (slot %s); "
                    "is the input non-differentiable?" %
                    (op.attrs["fwd_type"], names[i], slot))
    _bind_outputs(op, result, env)
    _maybe_sync_param_grads(op, env, ctx)
