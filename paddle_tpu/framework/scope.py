"""Scope: persistent name -> device-array storage.

Reference parity: paddle/fluid/framework/scope.{h,cc} + pybind global scope.
Parameters and optimizer state live here between Executor.run calls as
jax.Arrays (resident in TPU HBM); the Executor donates them into each step so
updates are in-place in XLA.
"""
import numpy as np


class Scope(object):
    def __init__(self):
        self._vars = {}

    def var(self, name):
        """Create-or-get slot (reference Scope::Var)."""
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name, None)

    def has_var(self, name):
        return name in self._vars

    def set_var(self, name, value):
        self._vars[name] = value

    def erase(self, name):
        self._vars.pop(name, None)

    def keys(self):
        return self._vars.keys()

    def items(self):
        return self._vars.items()

    def get_numpy(self, name):
        v = self._vars.get(name)
        return None if v is None else np.asarray(v)

    def new_scope(self):
        return Scope()

    def drop_kids(self):
        pass


_global_scope = Scope()


def global_scope():
    return _global_scope


import contextlib


@contextlib.contextmanager
def scope_guard(scope):
    global _global_scope
    old = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = old
