"""Static-graph IR: Program / Block / Operator / Variable / Parameter.

Reference parity: python/paddle/fluid/framework.py (Program, Block, Operator,
Variable, Parameter, program_guard, name_scope, default_main_program,
default_startup_program) and paddle/fluid/framework/{program_desc,block_desc,
op_desc}.cc + framework.proto.

TPU-first design notes:
 - The IR is pure Python and JSON-serializable (replaces framework.proto).
 - Ops carry a stable ``desc_id`` so a ``*_grad`` op can be paired with its
   forward op at trace time (single-forward-pass autodiff via jax.vjp, see
   framework/trace.py) the way the reference pairs GradOpDesc with OpDesc.
 - Shapes use -1 for the (dynamic) batch dim at build time, but every Program
   is traced with concrete feed shapes and compiled by XLA with static shapes.
"""
import contextlib
import copy
import itertools
import json

import numpy as np

from . import unique_name
from .dtypes import normalize_dtype

_desc_id_counter = itertools.count()

GRAD_VAR_SUFFIX = "@GRAD"


def grad_var_name(name):
    return name + GRAD_VAR_SUFFIX


class Variable(object):
    """A symbolic tensor in a Block.

    Reference parity: fluid.framework.Variable (VarDesc). LoD (ragged) levels
    are replaced by explicit mask/length tensors in the TPU design, so
    ``lod_level`` is kept only as API-compat metadata.
    """

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 persistable=False, stop_gradient=False, lod_level=0,
                 is_data=False, initializer=None, **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = normalize_dtype(dtype) if dtype is not None else None
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.lod_level = lod_level
        self.is_data = is_data
        # Optional jax.sharding PartitionSpec-like tuple, e.g. ("mp", None).
        self.sharding = kwargs.get("sharding", None)

    @property
    def is_parameter(self):
        return isinstance(self, Parameter)

    def astype(self, dtype):
        from ..layers import tensor as _tensor_layers
        return _tensor_layers.cast(self, dtype)

    def to_dict(self):
        d = {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "lod_level": self.lod_level,
            "is_data": self.is_data,
            "sharding": list(self.sharding) if self.sharding else None,
        }
        if self.is_parameter:
            d["is_parameter"] = True
            d["trainable"] = self.trainable
        return d

    def __repr__(self):
        return "Variable(%s, shape=%s, dtype=%s%s)" % (
            self.name, self.shape, self.dtype,
            ", persistable" if self.persistable else "")

    __str__ = __repr__

    # Math-op sugar (reference: layers/math_op_patch.py monkey patches these).
    def _binary(self, other, fn, reverse=False):
        from ..layers import nn as _nn, tensor as _tensor
        if not isinstance(other, Variable):
            other = _tensor.fill_constant(
                shape=[1], dtype=self.dtype, value=float(other))
        a, b = (other, self) if reverse else (self, other)
        return fn(a, b)

    def __add__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_add)

    __radd__ = __add__

    def __sub__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_sub)

    def __rsub__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_sub, reverse=True)

    def __mul__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_mul)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_div)

    def __rtruediv__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_div, reverse=True)

    def __pow__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_pow)

    def __floordiv__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_floordiv)

    def __mod__(self, other):
        from ..layers import nn
        return self._binary(other, nn.elementwise_mod)

    def astype(self, dtype):
        """Graph-level cast (reference math_op_patch astype)."""
        from ..layers import nn
        return nn.cast(self, dtype)

    def __neg__(self):
        from ..layers import nn
        return self.__mul__(-1.0)

    def __matmul__(self, other):
        from ..layers import nn
        return nn.matmul(self, other)

    def _cmp(self, other, op_type):
        from ..layers import control_flow
        return control_flow._compare(self, other, op_type)

    def __lt__(self, other):
        return self._cmp(other, "less_than")

    def __le__(self, other):
        return self._cmp(other, "less_equal")

    def __gt__(self, other):
        return self._cmp(other, "greater_than")

    def __ge__(self, other):
        return self._cmp(other, "greater_equal")


class Parameter(Variable):
    """A trainable, persistable Variable (reference: fluid Parameter)."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or any(s <= 0 for s in shape):
            raise ValueError("parameter shape must be static and positive, "
                             "got %s" % (shape,))
        kwargs.setdefault("persistable", True)
        super(Parameter, self).__init__(block, shape=shape, dtype=dtype, **kwargs)
        self.trainable = kwargs.get("trainable", True)
        self.regularizer = kwargs.get("regularizer", None)
        self.gradient_clip_attr = kwargs.get("gradient_clip_attr", None)
        self.do_model_average = kwargs.get("do_model_average", None)
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})


class Operator(object):
    """One op in a Block.

    inputs/outputs: dict slot-name -> list of var names (reference OpDesc).
    attrs must stay JSON-serializable (numbers, strings, bools, lists, and
    sub-block indices for control-flow ops).
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None,
                 desc_id=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        self.desc_id = desc_id if desc_id is not None else next(_desc_id_counter)

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def to_dict(self):
        return {"type": self.type, "inputs": self.inputs,
                "outputs": self.outputs, "attrs": _json_safe(self.attrs),
                "desc_id": self.desc_id}

    def __repr__(self):
        return "Operator(%s, in=%s, out=%s)" % (
            self.type, self.inputs, self.outputs)


def _json_safe(obj):
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return {"__ndarray__": obj.tolist(), "dtype": str(obj.dtype)}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    return obj


def _json_restore(obj):
    if isinstance(obj, dict):
        if "__ndarray__" in obj:
            return np.array(obj["__ndarray__"], dtype=obj["dtype"])
        return {k: _json_restore(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_json_restore(v) for v in obj]
    return obj


class Block(object):
    """An ordered list of ops plus a symbol table of vars."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}          # name -> Variable
        self.ops = []           # [Operator]

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs):
        param = Parameter(self, kwargs.pop("shape"), kwargs.pop("dtype"),
                          **kwargs)
        self.vars[param.name] = param
        return param

    def var(self, name):
        """Find var by name in this block (reference: Block.var raises)."""
        v = self.vars.get(name)
        if v is None:
            raise ValueError("var %r is not in block %d" % (name, self.idx))
        return v

    def _find_var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def has_var(self, name):
        return name in self.vars

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        # inside a pp_stage_guard (distributed/pipeline_program.py) every
        # appended op is stamped with its pipeline stage — the TPU-native
        # analogue of the reference's device_guard sections
        stage = getattr(self.program, "_pp_stage_ctx", None)
        if stage is not None and "pp_stage" not in op.attrs:
            op.attrs["pp_stage"] = int(stage)
        self.ops.append(op)
        self.program._version += 1
        return op

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._version += 1
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._version += 1
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._version += 1

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


class Program(object):
    """A whole computation: list of Blocks, block 0 is global.

    Reference parity: fluid.Program / ProgramDesc. ``_version`` is bumped on
    every mutation and is part of the Executor's compile-cache key.
    """

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0
        self.random_seed = 0
        self._op_role = "forward"   # forward | backward | optimize | lr_sched

    # ---- block management -------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None):
        parent_idx = (self.current_block_idx
                      if parent_idx is None else parent_idx)
        blk = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        self._version += 1
        return blk

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def block(self, idx):
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    # ---- introspection ----------------------------------------------------
    def all_parameters(self):
        return [p for blk in self.blocks for p in blk.all_parameters()]

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def __str__(self):
        return self.to_string()

    def to_string(self, throw_on_error=False, with_details=False):
        lines = []
        for blk in self.blocks:
            lines.append("-- block %d (parent %d) --" % (blk.idx, blk.parent_idx))
            for v in blk.vars.values():
                lines.append("  " + repr(v))
            for op in blk.ops:
                lines.append("  {%s} %s -> %s  attrs=%s" % (
                    op.type, op.inputs, op.outputs,
                    {k: v for k, v in op.attrs.items()
                     if not k.startswith("_")}))
        return "\n".join(lines)

    # ---- transforms -------------------------------------------------------
    def clone(self, for_test=False):
        """Deep-copy the program.

        ``for_test=True`` matches reference Program.clone(for_test=True):
        backward/optimize-role ops are dropped (running the clone must not
        update parameters) and remaining ops get ``is_test=True`` (dropout
        becomes identity, batch_norm uses the moving statistics).
        """
        p = Program.__new__(Program)
        p.blocks = []
        p.current_block_idx = 0
        p._version = 0
        p.random_seed = self.random_seed
        p._op_role = "forward"
        # vetted analysis exemptions (framework/analysis.allowlist) are
        # a property of the graph, not the object: a clone — including
        # clone(for_test=True) eval programs and _prune results — keeps
        # them, or every eval compile would re-flag (or strict-fail) a
        # diagnostic the builder already vetted
        allow = getattr(self, "_analysis_allowlist", None)
        if allow:
            p._analysis_allowlist = dict(allow)
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            for v in blk.vars.values():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[nv.name] = nv
            for op in blk.ops:
                # lr_sched covers the step-counter increment: evaluating
                # the clone must not advance the training LR schedule
                if for_test and op.attrs.get("op_role") in (
                        "backward", "optimize", "lr_sched"):
                    continue
                nop = Operator(nb, op.type, op.inputs, op.outputs,
                               copy.deepcopy(op.attrs), desc_id=op.desc_id)
                if for_test and "is_test" in nop.attrs:
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
            p.blocks.append(nb)
        return p

    def _prune(self, feeded_var_names, target_var_names):
        """Return a clone keeping only ops needed to compute targets from
        feeds (reference: Program._prune_with_input, used when freezing
        inference programs)."""
        pruned = self.clone()
        blk = pruned.global_block()
        needed = set(target_var_names)
        kept = []
        for op in reversed(blk.ops):
            if any(o in needed for o in op.output_names()):
                kept.append(op)
                for i in op.input_names():
                    if i not in feeded_var_names:
                        needed.add(i)
        kept.reverse()
        blk.ops = kept
        used = set(feeded_var_names) | set(target_var_names)
        for op in kept:
            used.update(op.input_names())
            used.update(op.output_names())
        blk.vars = {n: v for n, v in blk.vars.items() if n in used}
        pruned._version += 1
        return pruned

    # ---- serialization ----------------------------------------------------
    def to_dict(self):
        return {"format": "paddle_tpu.program.v1",
                "random_seed": self.random_seed,
                "blocks": [b.to_dict() for b in self.blocks]}

    def to_json(self):
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d):
        if d.get("format") != "paddle_tpu.program.v1":
            raise ValueError("not a paddle_tpu program: %r" % d.get("format"))
        p = Program.__new__(Program)
        p.blocks = []
        p.current_block_idx = 0
        p._version = 0
        p.random_seed = d.get("random_seed", 0)
        p._op_role = "forward"
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                vd = dict(vd)
                is_param = vd.pop("is_parameter", False)
                trainable = vd.pop("trainable", True)
                shape = vd.pop("shape")
                dtype = vd.pop("dtype")
                name = vd.pop("name")
                sharding = vd.pop("sharding", None)
                if is_param:
                    v = Parameter(blk, shape, dtype, name=name,
                                  trainable=trainable, **vd)
                else:
                    v = Variable(blk, name=name, shape=shape, dtype=dtype, **vd)
                v.sharding = tuple(sharding) if sharding else None
                blk.vars[v.name] = v
            for od in bd["ops"]:
                blk.ops.append(Operator(blk, od["type"], od["inputs"],
                                        od["outputs"],
                                        _json_restore(od["attrs"]),
                                        desc_id=od.get("desc_id")))
            p.blocks.append(blk)
        return p

    @staticmethod
    def from_json(s):
        return Program.from_dict(json.loads(s))


# ---- default programs / guards -------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()


def default_startup_program():
    return _startup_program_


def default_main_program():
    return _main_program_


def switch_main_program(program):
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program):
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()
