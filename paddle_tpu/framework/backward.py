"""Graph-level reverse-mode autodiff on a Program.

Reference parity: python/paddle/fluid/backward.py (append_backward,
gradients, _addup_repetitive_outputs_) + grad_op_desc_maker.h.

Algorithm (same shape as the reference):
  1. slice the block to ops that the loss transitively depends on;
  2. forward-propagate "grad-connected" (reachable from a trainable param,
     not stop_gradient);
  3. walk the slice in reverse, emitting one generic ``grad_of`` op per
     forward op, accumulating duplicate gradients with ``sum`` ops.

The ``grad_of`` op computes d(inputs) from d(outputs) via the forward op's
jax.vjp captured at trace time (framework/trace.py), which replaces the
reference's hand-written per-op grad kernels.
"""
from .dtypes import is_float
from .program import Parameter, grad_var_name
from .trace import EMPTY_VAR, GRAD_OP_TYPE
from ..ops.registry import get_op as _registry_get_op

_RENAME = "@RENAME@"


def _producer_sliced_ops(block, target_name):
    """Ops (in order) that target transitively depends on, ending at the
    last producer of target."""
    last = -1
    for i, op in enumerate(block.ops):
        if target_name in op.output_names():
            last = i
    if last < 0:
        raise ValueError("target var %r is not produced by any op in the "
                         "block; cannot differentiate" % target_name)
    needed = {target_name}
    keep = [False] * (last + 1)
    for i in range(last, -1, -1):
        op = block.ops[i]
        if op.type == GRAD_OP_TYPE:
            continue
        if any(o in needed for o in op.output_names()):
            keep[i] = True
            needed.update(op.input_names())
    return [block.ops[i] for i in range(last + 1) if keep[i]]


def _connected_set(block, sliced_ops, roots, no_grad_set):
    from ..ops.registry import get_op, has_op
    connected = set(roots) - no_grad_set
    for op in sliced_ops:
        if has_op(op.type) and not get_op(op.type).differentiable:
            continue
        if not any(n in connected for n in op.input_names()):
            continue
        for n in op.output_names():
            if n in no_grad_set:
                continue
            v = block._find_var_recursive(n)
            if v is not None and v.stop_gradient:
                continue
            if v is not None and not is_float(v.dtype):
                continue
            connected.add(n)
    return connected


class _GradAccumulator(object):
    """Tracks per-var gradient contributions; names them var@GRAD,
    var@GRAD@RENAME@1, ... and emits a sum op when there are several."""

    def __init__(self, block):
        self.block = block
        self.contribs = {}

    def next_name(self, var_name):
        lst = self.contribs.setdefault(var_name, [])
        g = grad_var_name(var_name)
        name = g if not lst else g + _RENAME + str(len(lst))
        lst.append(name)
        return name

    def finalize(self, var_name):
        """Return the final grad name for var (emitting sum if needed),
        or None if no contribution exists."""
        lst = self.contribs.get(var_name)
        if not lst:
            return None
        g = grad_var_name(var_name)
        if len(lst) > 1:
            self.block.append_op(
                "sum", inputs={"X": list(lst)}, outputs={"Out": [g]},
                attrs={"op_role": "backward"})
            self.contribs[var_name] = [g]
        return g


def _ensure_grad_var(block, base_name, grad_name):
    if not block.has_var(grad_name):
        base = block._find_var_recursive(base_name)
        block.create_var(name=grad_name,
                         shape=base.shape if base is not None else None,
                         dtype=base.dtype if base is not None else "float32",
                         persistable=False, stop_gradient=True)
    return block.vars.get(grad_name)


def calc_gradient_in_block(block, target, roots, no_grad_set,
                           target_grad_name=None):
    """Core engine shared by append_backward() and gradients()."""
    no_grad_set = set(no_grad_set or ())
    sliced = _producer_sliced_ops(block, target.name)
    connected = _connected_set(block, sliced, roots, no_grad_set)
    if target.name not in connected:
        return {}

    acc = _GradAccumulator(block)
    # seed d(target) = 1 (or the user-provided cotangent)
    if target_grad_name is None:
        seed_name = acc.next_name(target.name)
        _ensure_grad_var(block, target.name, seed_name)
        block.append_op(
            "fill_any_like", inputs={"X": [target.name]},
            outputs={"Out": [seed_name]},
            attrs={"value": 1.0, "op_role": "backward"})
    else:
        acc.contribs[target.name] = [target_grad_name]

    for op in reversed(sliced):
        in_names = op.input_names()
        if not any(n in connected and n not in no_grad_set
                   for n in in_names):
            continue
        # finalize output grads
        og = {}
        any_og = False
        for slot, names in op.outputs.items():
            lst = []
            for n in names:
                g = acc.finalize(n) if n in connected or n == target.name \
                    else None
                g = g if g is not None else EMPTY_VAR
                any_og = any_og or g != EMPTY_VAR
                lst.append(g)
            og["OG:" + slot] = lst
        if not any_og:
            continue

        # slots the kernel declares non-differentiable never receive a
        # grad from the trace-time vjp — registering a name for them
        # would leave a dangling @RENAME contribution that the sum op
        # later fails to find (e.g. a connected var feeding
        # fill_constant_batch_size_like's shape-only Input)
        try:
            nondiff_slots = set(_registry_get_op(op.type).nondiff)
        except NotImplementedError:
            # structural ops (feed/fetch-style) with no kernel entry
            nondiff_slots = set()
        ig = {}
        for slot, names in op.inputs.items():
            lst = []
            for n in names:
                if slot not in nondiff_slots and n in connected and \
                        n not in no_grad_set:
                    gname = acc.next_name(n)
                    _ensure_grad_var(block, n, gname)
                    lst.append(gname)
                else:
                    lst.append(EMPTY_VAR)
            if any(x != EMPTY_VAR for x in lst):
                ig["IG:" + slot] = lst
        if not ig:
            continue

        grad_inputs = {"X:" + slot: names for slot, names in op.inputs.items()}
        grad_inputs.update(og)
        block.append_op(
            GRAD_OP_TYPE, inputs=grad_inputs, outputs=ig,
            attrs={"fwd_type": op.type, "fwd_id": op.desc_id,
                   "fwd_attrs": dict(op.attrs), "op_role": "backward"})

    # finalize leaf grads (roots)
    out = {}
    for r in roots:
        g = acc.finalize(r)
        if g is not None:
            out[r] = g
    return out


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Append backward ops computing d(loss)/d(param) for every trainable
    parameter. Returns [(param_var, grad_var)].

    Reference parity: fluid.backward.append_backward.
    """
    block = loss.block
    program = block.program
    if parameter_list is not None:
        roots = [p.name if hasattr(p, "name") else p for p in parameter_list]
    else:
        roots = [p.name for p in program.all_parameters()
                 if getattr(p, "trainable", True)]
    grad_map = calc_gradient_in_block(block, loss, roots,
                                      set(no_grad_set or ()))
    result = []
    for r in roots:
        g = grad_map.get(r)
        if g is None:
            continue
        param = block._find_var_recursive(r)
        gvar = block.vars.get(g) or _ensure_grad_var(block, r, g)
        result.append((param, gvar))
    if not result:
        raise ValueError(
            "append_backward: no parameter receives a gradient from %r "
            "(is every path stop_gradient?)" % loss.name)
    return result


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """d(targets)/d(inputs). Reference parity: fluid.gradients."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    block = targets[0].block
    roots = [v.name for v in inputs]
    merged = {}
    for t, tg in zip(targets, target_gradients):
        gm = calc_gradient_in_block(
            block, t, roots, set(no_grad_set or ()),
            target_grad_name=tg.name if tg is not None else None)
        for r, g in gm.items():
            if r in merged:
                # sum contributions across targets
                s = grad_var_name(r) + "@MULTI_TARGET"
                block.append_op("sum", inputs={"X": [merged[r], g]},
                                outputs={"Out": [s]},
                                attrs={"op_role": "backward"})
                _ensure_grad_var(block, r, s)
                merged[r] = s
            else:
                merged[r] = g
    return [block.vars.get(merged[v.name]) if v.name in merged else None
            for v in inputs]
