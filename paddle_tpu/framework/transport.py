"""Socket-backed pod rendezvous — the network transport under
:class:`~.coordination.SocketCoordinator`.

Reference parity: the reference pod coordinates over the network (the
pserver/brpc RPC tier — trainers and pservers share no filesystem, only
sockets). FileCoordinator ports the *protocol* but not the transport: it
assumes a shared directory, and it only learns a host died when someone
*declares* it. This module supplies the real thing with nothing but the
stdlib:

  * :class:`CoordServer` — one small TCP service holding the
    coordination KV state: gather rounds (with the STICKY completion
    semantics of Local/FileCoordinator: the first completion freezes the
    member snapshot for every participant), tombstones (fencing), join
    announcements, and per-host heartbeats. A background monitor
    tombstones any registered host whose heartbeat goes stale past
    ``hb_deadline_s`` — liveness becomes a property of the transport,
    not of someone calling ``mark_lost``. Runnable in-process for tests
    (``CoordServer(n).start()``) or standalone via ``tools/coordsvc.py``.
  * :class:`CoordClient` — a tiny request/response client. Transient
    socket errors are retried through the shared
    :class:`~.resilience.RetryPolicy` (reconnect, then re-send — every
    server op is idempotent, round contributions keyed by
    ``(name, host_id)`` plus a client token so a replay after a broken
    pipe never double-counts and an imposter never overwrites). A
    daemon heartbeat thread keeps this host live and feeds the
    observability gauges.

Replication (coordination-plane HA): the service itself is no longer a
single point of failure. A *replication group* is an ordered list of
endpoints — one PRIMARY plus N warm STANDBYS, wired by
``configure_replication(index, peers, standby=)`` (or ``coordsvc
--peers/--repl-index/--standby``). The group is TERM-numbered:

  * the primary streams every state-mutating op (hello, gather
    contributions, tombstones, unfence, join announcements, put_info,
    heartbeat leases) to each standby over the same newline-JSON wire
    discipline, bootstrapping a late/behind standby from a full state
    snapshot; round-freezing ops are replicated SYNCHRONOUSLY (bounded
    by ``repl_sync_timeout_s`` — a dead standby is dropped from the
    wait set, availability over lockstep) so a promoted standby never
    rewinds a contribution a client was told landed;
  * on primary loss — judged by the SAME ``hb_deadline_s`` staleness
    bound the monitor fences hosts by — the lowest-index live standby
    promotes with a bumped term and refreshes every liveness lease
    (failover grace: clients must not be fenced for the primary's
    death);
  * every response carries the term, so a stale ex-primary that wakes
    up is fenced by CLIENTS (a lower term than one already observed is
    refused and the client fails over), and by PEERS (its replication
    stream is rejected with the higher term and it demotes itself to
    standby).

:class:`CoordClient` (and therefore ``SocketCoordinator`` and the whole
serving fleet) accepts a LIST of endpoints — "h:p1,h:p2" or a list —
and fails over transparently inside its retry budget: round
re-submission is idempotent keyed by ``(name, host_id)`` + token, so a
contribution replayed against the promoted standby is a no-op.

Single-node durability: ``snapshot_path=`` (``coordsvc
--snapshot-path``) persists periodic state snapshots and reloads on
start, so a SUPERVISED RESTART resumes in-flight rounds instead of
aborting them (liveness leases are refreshed on load — restart grace).

Wire protocol: newline-delimited JSON, one request object per line, one
response object per line, connections long-lived. Values are anything
JSON encodes — the same envelope FileCoordinator already writes to its
round files.

Observability (rides ``resilience.metrics()``):
  transport_reconnects_total   counter — client reconnect attempts
  transport_failovers_total    counter — client endpoint failovers that
                               reached a serving (promoted) member
  transport_heartbeat_lag      per-host gauge — seconds a host's
                               heartbeat cadence is running behind
                               (0 when healthy; grows during stalls)
  transport_term               gauge — the replication term last
                               observed (clients per host; the server
                               on every promote/demote)
  transport_replication_lag    gauge — ops the furthest-behind in-sync
                               standby trails the primary
"""
import collections
import json
import os
import socket
import socketserver
import threading
import time

from . import faultinject
from .coordination import GROW_FENCE_REASON
from .resilience import RetryPolicy, record_buddy_resident, record_event

__all__ = ["TransportError", "CoordServer", "CoordClient",
           "replicated_group", "MailboxServer", "mailbox_request"]

_DEFAULT_HB_INTERVAL_S = 0.5
# ops the primary must confirm on the standbys before answering the
# client (round contributions, tombstones, membership): everything a
# promoted standby must never rewind. hb/ack are ASYNC — leases are
# refreshed at promotion anyway, and a lost ack only delays cleanup.
_SYNC_CMDS = frozenset(("hello", "mark_lost", "announce_join",
                        "unfence", "put", "put_info", "put_blob",
                        "put_buddy_meta", "mailbox_hello",
                        "resize"))
_MUTATING_CMDS = _SYNC_CMDS | frozenset(("hb", "ack"))
_REPL_CMDS = frozenset(("repl_sync", "repl_apply", "repl_snapshot",
                        "repl_hb"))


class TransportError(ConnectionError):
    """The coordination service could not be reached (after retries).
    Subclasses ConnectionError so resilience.classify treats it as
    transient — the caller's RetryPolicy decides when to give up."""


def _split_addr(address):
    host, _, port = str(address).rpartition(":")
    return (host or "127.0.0.1", int(port))


def _blob_nbytes(blob):
    """Resident size of one legacy put_blob payload (the base64 npz
    text dominates; non-dict payloads are sized by their repr)."""
    if isinstance(blob, dict):
        return len(blob.get("npz", ""))
    return 0 if blob is None else len(str(blob))


def _record_coord_resident(state):
    """Export what THIS coordinator process holds for the buddy tier
    (legacy blob payloads + the p2p metadata table) as the
    ``buddy_resident_bytes{host="coord"}`` gauge — the memory-ceiling
    regression gate serving_probe --strict enforces. Callers hold
    ``state.lock``."""
    n = sum(_blob_nbytes(rec.get("blob"))
            for rec in state.blobs.values())
    n += len(json.dumps(
        {str(h): rec for h, rec in state.buddy_meta.items()}))
    record_buddy_resident("coord", n)


def _probe_status(address, timeout_s=1.0):
    """One-shot ``status`` probe against a group member; None when the
    member is unreachable (the promotion dance treats that as dead)."""
    try:
        with socket.create_connection(_split_addr(address),
                                      timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            s.sendall(json.dumps({"cmd": "status"}).encode() + b"\n")
            line = s.makefile("rb").readline()
        return json.loads(line) if line else None
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _PodState(object):
    """The coordination KV state, guarded by one lock.

    Mirrors FileCoordinator's directory layout in memory:
      lost:   {host_id: reason}           tombstones (fencing)
      joins:  {host_id: nonce}            fenced hosts asking back in
      rounds: {name: {"values", "tokens", "done", "acks"}}
      hb:     {host_id: last monotonic}   heartbeats (hello/hb)
      info:   {host_id: blob}             member-published JSON blobs
                                          (serving address, generation —
                                          see ``put_info``/``members``)
    ``completed`` keeps the most recent frozen round names (bounded
    deque — a long-running service must not grow by one string per
    round forever) for test and tooling introspection.

    Replication metadata lives here too, under the same lock:
    ``role`` ("primary"/"standby" — solo servers are always primary),
    ``term`` (bumped on every promotion; every response carries it) and
    ``applied_seq`` (the replication stream position — on the primary
    the next op gets ``applied_seq + 1``; a standby applies in exactly
    that order or asks for a snapshot).

    ``n_hosts=None`` starts the service in AUTO-SIZE mode: the pod size
    is learned from the first ``hello`` that carries ``n_hosts`` (every
    SocketCoordinator sends it), and every later hello must agree.
    Until then only ``hello`` is served — any other op would need the
    size for range checks and round completion.
    """

    def __init__(self, n_hosts, hb_deadline_s=None):
        self.n_hosts = None if n_hosts is None else int(n_hosts)
        self.hb_deadline_s = None if hb_deadline_s is None \
            else float(hb_deadline_s)
        self.lock = threading.Lock()
        self.lost = {}
        # bumped on EVERY membership mutation (tombstone and unfence):
        # clients order the lost maps they observe by it, so a stale
        # response processed late can never resurrect a cleared
        # tombstone (or re-fire loss hooks for a readmitted host)
        self.lost_version = 0
        # bumped on every accepted ``resize``: the hello mismatch error
        # names a resized group explicitly (a stale-size client must
        # relaunch with the current size, never land phantom state)
        self.resize_version = 0
        self.joins = {}
        self.rounds = {}
        self.hb = {}
        self.info = {}
        # buddy-checkpoint mailboxes: {owner: {"gen", "buddy", "blob"}}.
        # Bounded by construction — ONE generation per owner, overwritten
        # in place every window (put_blob refuses a gen rewind). An
        # entry models a replica living in the buddy host's RAM, so it
        # is evicted only when owner AND buddy are both tombstoned —
        # the one case where nobody holds the bytes anymore.
        self.blobs = {}
        # legacy-mailbox payload ceiling: put_blob refuses a single
        # payload above this many bytes with a NAMED error instead of
        # letting a misconfigured legacy-mode pod grow the coordinator
        # until the OOM killer arrives. None disables the check.
        self.blob_max_bytes = None
        # p2p buddy tier: the coordinator holds only this METADATA
        # table — {owner: {"gen", "buddy", "digest", "nbytes"}} — while
        # payloads live in the hosts' own MailboxServer endpoints,
        # registered in mailbox_addrs ({host: "ip:port"}). Same
        # generation fence and double-tombstone eviction as blobs.
        self.buddy_meta = {}
        self.mailbox_addrs = {}
        self.completed = collections.deque(maxlen=2048)
        self.role = "primary"
        self.term = 0
        self.applied_seq = 0
        # heartbeat scans are HELD OFF until this monotonic instant: a
        # freshly promoted (or snapshot-restored) member must give
        # every client a full deadline of grace to re-dial before it
        # may fence anyone — their silence was the OLD primary's
        # death, not theirs
        self.scan_holdoff = 0.0

    # -- callers hold self.lock ------------------------------------------
    def _mark_lost(self, host_id, reason):
        if host_id in self.lost:
            return False
        self.lost[host_id] = str(reason)
        self.lost_version += 1
        self.joins.pop(host_id, None)
        self._evict_orphan_blobs()
        return True

    def _evict_orphan_blobs(self):
        """Drop buddy snapshots whose owner AND recorded buddy are both
        tombstoned: in the physical system those bytes lived in the
        buddy's RAM, so a double failure loses them — keeping the
        mailbox would let a restore adopt state no live host vouches
        for. A dead owner whose buddy is alive keeps its mailbox:
        that IS the buddy-restore case."""
        for owner in [o for o, rec in self.blobs.items()
                      if o in self.lost and rec["buddy"] in self.lost]:
            del self.blobs[owner]
        for owner in [o for o, rec in self.buddy_meta.items()
                      if o in self.lost and rec["buddy"] in self.lost]:
            del self.buddy_meta[owner]

    def _scan_heartbeats(self, now):
        """Tombstone every registered, un-fenced host whose heartbeat is
        older than the deadline. Returns the newly lost ids."""
        if self.hb_deadline_s is None or now < self.scan_holdoff:
            return []
        newly = []
        for hid, last in list(self.hb.items()):
            if hid in self.lost:
                continue
            age = now - last
            if age > self.hb_deadline_s:
                if self._mark_lost(hid, "missed heartbeat (%.2fs > %.2fs)"
                                   % (age, self.hb_deadline_s)):
                    newly.append(hid)
        return newly

    def _freeze_if_complete(self, name):
        """STICKY completion (Local/FileCoordinator parity): the first
        observation of every live host present freezes the member
        snapshot; later membership changes cannot re-open the round."""
        r = self.rounds.get(name)
        if r is None or r["done"] is not None:
            return
        present = set(r["values"])
        waiting = [i for i in range(self.n_hosts)
                   if i not in self.lost and i not in present]
        if waiting:
            return
        r["done"] = sorted(present - set(self.lost))
        self.completed.append(name)

    # -- snapshot ser/de (callers hold self.lock) -------------------------
    def to_snapshot(self):
        """JSON-ready full-state snapshot: the standby bootstrap payload
        AND the on-disk restart format (one encoding, two consumers).
        Heartbeat leases travel as the SET of leased hosts, not their
        ages — monotonic clocks do not cross processes, and the loader
        refreshing every lease to its own ``now`` is exactly the
        restart/failover grace clients need to re-dial."""
        return {
            "v": 1,
            "n_hosts": self.n_hosts,
            "term": self.term,
            "seq": self.applied_seq,
            "lost": {str(h): r for h, r in self.lost.items()},
            "lost_version": self.lost_version,
            "resize_version": self.resize_version,
            "joins": {str(h): n for h, n in self.joins.items()},
            "rounds": {
                name: {"values": {str(h): v
                                  for h, v in r["values"].items()},
                       "tokens": {str(h): t
                                  for h, t in r["tokens"].items()},
                       "done": r["done"],
                       "acks": sorted(r["acks"])}
                for name, r in self.rounds.items()},
            "info": {str(h): v for h, v in self.info.items()},
            "blobs": {str(h): rec for h, rec in self.blobs.items()},
            "buddy_meta": {str(h): rec
                           for h, rec in self.buddy_meta.items()},
            "mailbox_addrs": {str(h): a
                              for h, a in self.mailbox_addrs.items()},
            "hb_hosts": sorted(self.hb),
            "completed": list(self.completed),
        }

    def load_snapshot(self, snap, now):
        """Adopt a full snapshot (standby bootstrap / restart resume).
        Every leased host's heartbeat is refreshed to ``now`` so the
        grace period for clients to re-dial starts here, not at some
        other process's epoch."""
        self.n_hosts = None if snap.get("n_hosts") is None \
            else int(snap["n_hosts"])
        self.term = int(snap.get("term", 0))
        self.applied_seq = int(snap.get("seq", 0))
        self.lost = {int(h): r for h, r in snap.get("lost", {}).items()}
        self.lost_version = int(snap.get("lost_version", 0))
        # absent in PR 9-era snapshots: groups that never resize stay
        # wire-compatible (default 0 == never resized)
        self.resize_version = int(snap.get("resize_version", 0))
        self.joins = {int(h): int(n)
                      for h, n in snap.get("joins", {}).items()}
        self.rounds = {
            name: {"values": {int(h): v
                              for h, v in r.get("values", {}).items()},
                   "tokens": {int(h): t
                              for h, t in r.get("tokens", {}).items()},
                   "done": r.get("done"),
                   "acks": set(r.get("acks", ()))}
            for name, r in snap.get("rounds", {}).items()}
        self.info = {int(h): v for h, v in snap.get("info", {}).items()}
        # absent in pre-buddy snapshots (default: no mailboxes)
        self.blobs = {int(h): rec
                      for h, rec in snap.get("blobs", {}).items()}
        # absent in pre-p2p snapshots (default: no p2p metadata)
        self.buddy_meta = {int(h): rec
                           for h, rec in
                           snap.get("buddy_meta", {}).items()}
        self.mailbox_addrs = {int(h): a
                              for h, a in
                              snap.get("mailbox_addrs", {}).items()}
        self.hb = {int(h): now for h in snap.get("hb_hosts", ())}
        if self.hb_deadline_s is not None:
            # restart grace, same reasoning as the promotion holdoff
            self.scan_holdoff = now + self.hb_deadline_s
        self.completed = collections.deque(snap.get("completed", ()),
                                           maxlen=2048)


# ---------------------------------------------------------------------------
# replication engine (primary streaming + standby promotion)
# ---------------------------------------------------------------------------

class _Replication(object):
    """The warm-standby engine of one group member.

    Owns the per-peer sender threads (primary side: stream ops, push
    snapshots, collect acks) and the promotion watcher (standby side:
    judge the primary dead by the heartbeat staleness bound, defer to
    lower-index live standbys, promote with a bumped term). Role and
    term live on the shared ``_PodState`` under ITS lock; the op log
    and ack bookkeeping live here under ``self.cond``. Lock order:
    ``state.lock`` may be held when taking ``self.cond``, NEVER the
    reverse."""

    LOG_CAP = 4096

    def __init__(self, server, index, peers, standby,
                 sync_timeout_s=2.0):
        self.server = server
        self.state = server._state
        self.index = int(index)
        if isinstance(peers, dict):
            all_peers = {int(i): str(a) for i, a in peers.items()}
        else:
            all_peers = {i: str(a) for i, a in enumerate(peers)}
        # peers = every OTHER member, keyed by its group index; the
        # index order IS the promotion priority
        self.peers = {i: a for i, a in all_peers.items()
                      if i != self.index}
        self.cond = threading.Condition()
        self.log = collections.deque(maxlen=self.LOG_CAP)  # (seq, op)
        self.acked = {}
        self.in_sync = {}
        self.sync_timeout_s = float(sync_timeout_s)
        self.last_stream = time.monotonic()
        self.primary_index = None if standby else self.index
        self._lag_rec_t = 0.0
        self._stop = threading.Event()
        self._threads = []
        self.state.role = "standby" if standby else "primary"

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._discover_incumbent()
        for pidx, addr in sorted(self.peers.items()):
            t = threading.Thread(target=self._sender_main,
                                 args=(pidx, addr), daemon=True,
                                 name="paddle_tpu-repl-%d>%d"
                                 % (self.index, pidx))
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._watch_main, daemon=True,
                             name="paddle_tpu-repl-watch-%d"
                             % self.index)
        t.start()
        self._threads.append(t)

    def stop(self, join=True):
        self._stop.set()
        with self.cond:
            self.cond.notify_all()
        if join:
            for t in self._threads:
                t.join(timeout=5.0)

    def _discover_incumbent(self):
        """Startup term discovery: a member booted as primary (e.g. a
        restarted ex-primary relaunched with its ORIGINAL flags) probes
        its peers first — finding a higher term, or a live primary at
        its own term, it starts as a STANDBY instead of splitting the
        brain. Fresh groups find nothing and keep their configured
        roles."""
        with self.state.lock:
            if self.state.role != "primary" or not self.peers:
                return
            my_term = self.state.term
        best = None
        for pidx, addr in sorted(self.peers.items()):
            st = _probe_status(addr)
            if not st:
                continue
            t = int(st.get("term", 0))
            if t > my_term or (st.get("role") == "primary"
                               and t >= my_term):
                if best is None or t > best[0]:
                    best = (t, pidx)
        if best is None:
            return
        with self.state.lock:
            self.state.term = max(self.state.term, best[0])
            self.state.role = "standby"
            self.primary_index = best[1]
            self.last_stream = time.monotonic()
            term = self.state.term
        record_event("transport_demote", index=self.index, term=term,
                     reason="incumbent")
        record_event("transport_term", term=term)

    # -- primary side ------------------------------------------------------
    def publish_locked(self, seq, op):
        """Append one op to the stream (caller holds ``state.lock``;
        the seq was already taken from ``state.applied_seq``)."""
        with self.cond:
            self.log.append((seq, op))
            self.cond.notify_all()

    def wait_replicated(self, target_seq, timeout_s):
        """Block until every IN-SYNC standby acked ``target_seq``. On
        timeout the laggards are dropped from the sync set (they will
        re-position — possibly via snapshot — when they catch up or
        reconnect): a dead standby must cost one bounded wait, not the
        pod's availability."""
        deadline = time.monotonic() + float(timeout_s)
        with self.cond:
            while not self._stop.is_set():
                waiting = [p for p in self.peers
                           if self.in_sync.get(p)
                           and self.acked.get(p, 0) < target_seq]
                if not waiting:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    for p in waiting:
                        self.in_sync[p] = False
                    record_event("transport_repl_desync",
                                 peers=sorted(waiting),
                                 seq=target_seq)
                    return False
                self.cond.wait(remaining)
        return False

    def _ack(self, pidx, have):
        with self.state.lock:
            head = self.state.applied_seq
        now = time.monotonic()
        with self.cond:
            self.acked[pidx] = have
            self.in_sync[pidx] = True
            self.cond.notify_all()
            lag = max((head - self.acked.get(p, 0)
                       for p in self.peers if self.in_sync.get(p)),
                      default=0)
            due = now - self._lag_rec_t > 1.0
            if due:
                self._lag_rec_t = now
        # the gauge event is throttled like the hb-lag one: the event
        # log is bounded and acks run at op rate
        if due:
            record_event("transport_repl_lag", lag=lag)

    def _next_entry(self, sent, timeout_s):
        """The next op past ``sent``: an (seq, op) entry, "snapshot"
        when the log window no longer covers the gap, or None on idle
        timeout (the sender then heartbeats)."""
        deadline = time.monotonic() + timeout_s
        with self.cond:
            while not self._stop.is_set():
                if self.log:
                    first = self.log[0][0]
                    if sent + 1 < first:
                        return "snapshot"
                    idx = sent + 1 - first
                    if idx < len(self.log):
                        return self.log[idx]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self.cond.wait(remaining)
        return None

    @staticmethod
    def _rpc(sock, rfile, req):
        sock.sendall(json.dumps(req).encode() + b"\n")
        line = rfile.readline()
        if not line:
            raise ConnectionError("replication peer closed the stream")
        return json.loads(line)

    def _observe_higher_term(self, term, pidx=None):
        """A peer answered with a term beyond ours: adopt it, and if we
        were primary, DEMOTE — we are the stale ex-primary the fencing
        exists for. The watcher takes over from here (it may promote us
        again later if the whole group ahead of us dies)."""
        demoted = False
        with self.state.lock:
            if term > self.state.term:
                self.state.term = term
                if self.state.role == "primary":
                    self.state.role = "standby"
                    demoted = True
                self.primary_index = pidx
                self.last_stream = time.monotonic()
            new_term = self.state.term
        if demoted:
            record_event("transport_demote", index=self.index,
                         term=new_term, reason="higher_term")
            record_event("transport_term", term=new_term)

    def _send_snapshot(self, sock, rfile, term):
        with self.state.lock:
            snap = self.state.to_snapshot()
        resp = self._rpc(sock, rfile, {"cmd": "repl_snapshot",
                                       "term": term,
                                       "index": self.index,
                                       "state": snap})
        if resp.get("repl_reject"):
            self._observe_higher_term(int(resp.get("term", 0)))
            raise ConnectionError("snapshot rejected (stale term)")
        return int(resp.get("have", snap["seq"]))

    def _sender_main(self, pidx, addr):
        """One peer's replication stream: position (sync/snapshot),
        then apply-op/heartbeat forever. Parked while this member is a
        standby; reconnects with a small backoff on socket loss."""
        backoff = 0.05
        sock = rfile = None
        sent = -1

        def drop():
            for c in (rfile, sock):
                try:
                    if c is not None:
                        c.close()
                except OSError:
                    pass
            with self.cond:
                self.in_sync[pidx] = False
                self.cond.notify_all()

        hb_s = self.state.hb_deadline_s
        idle_s = max(0.05, hb_s / 4.0) if hb_s else 0.5
        while not self._stop.is_set():
            with self.state.lock:
                role = self.state.role
                term = self.state.term
                head = self.state.applied_seq
            if role != "primary":
                if sock is not None:
                    drop()
                    sock = rfile = None
                self._stop.wait(0.2)
                continue
            try:
                if sock is None:
                    sock = socket.create_connection(_split_addr(addr),
                                                    timeout=2.0)
                    sock.settimeout(max(2.0, self.sync_timeout_s * 2))
                    rfile = sock.makefile("rb")
                    resp = self._rpc(sock, rfile,
                                     {"cmd": "repl_sync", "term": term,
                                      "seq": head, "index": self.index})
                    if resp.get("repl_reject"):
                        self._observe_higher_term(
                            int(resp.get("term", 0)), pidx)
                        raise ConnectionError("sync rejected")
                    have = int(resp.get("have", 0))
                    with self.cond:
                        covered = bool(self.log) \
                            and self.log[0][0] <= have + 1
                    if have < head and not covered:
                        have = self._send_snapshot(sock, rfile, term)
                    sent = have
                    self._ack(pidx, sent)
                entry = self._next_entry(sent, idle_s)
                if entry == "snapshot":
                    sent = self._send_snapshot(sock, rfile, term)
                    self._ack(pidx, sent)
                    continue
                if entry is None:
                    resp = self._rpc(sock, rfile,
                                     {"cmd": "repl_hb", "term": term,
                                      "seq": head, "index": self.index})
                else:
                    seq, op = entry
                    resp = self._rpc(sock, rfile,
                                     {"cmd": "repl_apply", "term": term,
                                      "seq": seq, "index": self.index,
                                      "op": op})
                if resp.get("repl_reject"):
                    self._observe_higher_term(
                        int(resp.get("term", 0)), pidx)
                    raise ConnectionError("stream rejected")
                if resp.get("need_snapshot"):
                    sent = self._send_snapshot(sock, rfile, term)
                else:
                    sent = int(resp.get("have", sent))
                self._ack(pidx, sent)
                backoff = 0.05
            except (OSError, ValueError):
                drop()
                sock = rfile = None
                sent = -1
                self._stop.wait(backoff)
                backoff = min(0.5, backoff * 2.0)
        drop()

    # -- standby side ------------------------------------------------------
    def _watch_main(self):
        """Promotion watcher: while standby, judge the primary by the
        SAME heartbeat staleness bound hosts are fenced by; on
        staleness, defer to any lower-index live standby (the
        lowest-index live standby promotes), and never promote past a
        primary that still answers its status probe."""
        dl = self.state.hb_deadline_s
        if dl is None:
            return   # liveness disabled: promotion is manual-only
        period = max(0.02, dl / 4.0)
        while not self._stop.wait(period):
            with self.state.lock:
                role = self.state.role
                term = self.state.term
            if role != "standby":
                continue
            if time.monotonic() - self.last_stream <= dl:
                continue
            statuses = {}
            for pidx, addr in sorted(self.peers.items()):
                st = _probe_status(addr, timeout_s=max(0.2, dl / 4.0))
                if st:
                    statuses[pidx] = st
            if any(st.get("role") == "primary"
                   and int(st.get("term", 0)) >= term
                   for st in statuses.values()):
                # a live primary exists — our stream is partitioned,
                # not orphaned. Reset the staleness clock and keep
                # waiting: promoting here WOULD be the split brain.
                self.last_stream = time.monotonic()
                continue
            if any(pidx < self.index and st.get("role") == "standby"
                   for pidx, st in statuses.items()):
                continue   # a lower-index live standby will promote
            self._promote()

    def _promote(self):
        with self.state.lock:
            if self.state.role != "standby":
                return
            self.state.term += 1
            self.state.role = "primary"
            term = self.state.term
            now = time.monotonic()
            # failover grace: every lease restarts NOW — plus a full
            # extra deadline of scan holdoff, because a client deep in
            # its reconnect backoff may take longer than one deadline
            # to land its first post-promotion heartbeat
            for h in list(self.state.hb):
                self.state.hb[h] = now
            if self.state.hb_deadline_s is not None:
                self.state.scan_holdoff = \
                    now + self.state.hb_deadline_s
            self.primary_index = self.index
            with self.cond:
                # the promoted log starts empty at applied_seq: peers
                # behind it re-position via snapshot
                self.log.clear()
                self.acked = {}
                self.in_sync = {}
                self.cond.notify_all()
        record_event("transport_promote", index=self.index, term=term)
        record_event("transport_term", term=term)

    # -- repl request handling (both sides; caller holds state.lock) ------
    def handle_locked(self, state, req, now):
        cmd = req.get("cmd")
        term = int(req.get("term", 0))
        pidx = req.get("index")
        pidx = None if pidx is None else int(pidx)
        if term < state.term:
            # THE ex-primary fence: a stale incarnation's stream is
            # refused with the new term; it demotes itself on sight
            return {"repl_reject": True, "term": state.term}
        if term == state.term and state.role == "primary":
            # two primaries at one term (a promotion race): the LOWER
            # index wins outright — deterministic, no negotiation
            if pidx is not None and pidx < self.index:
                state.role = "standby"
                record_event("transport_demote", index=self.index,
                             term=state.term, reason="tie_break")
            else:
                return {"repl_reject": True, "term": state.term}
        if term > state.term:
            state.term = term
            if state.role == "primary":
                state.role = "standby"
                record_event("transport_demote", index=self.index,
                             term=term, reason="higher_term")
            record_event("transport_term", term=term)
        self.last_stream = time.monotonic()
        if pidx is not None:
            self.primary_index = pidx
        if cmd in ("repl_sync", "repl_hb"):
            return {"ok": True, "have": state.applied_seq,
                    "term": state.term}
        if cmd == "repl_apply":
            seq = int(req.get("seq", 0))
            if seq <= state.applied_seq:
                return {"ok": True, "have": state.applied_seq}
            if seq == state.applied_seq + 1:
                _apply_replicated(state, req.get("op") or {}, now)
                state.applied_seq = seq
                return {"ok": True, "have": seq}
            return {"need_snapshot": True, "have": state.applied_seq}
        if cmd == "repl_snapshot":
            state.load_snapshot(req.get("state") or {}, now)
            state.term = max(state.term, term)
            state.role = "standby"
            return {"ok": True, "have": state.applied_seq}
        return {"error": "unknown repl cmd %r" % cmd}

    def primary_hint(self):
        """The current primary's address, best-effort (a standby knows
        it from the stream metadata; None before the first contact —
        or once the stream has gone STALE: hinting clients at a
        primary we ourselves judge dead would ping-pong them between
        a refused connection and this redirect for the whole
        promotion window)."""
        if self.primary_index is None:
            return None
        if self.primary_index == self.index:
            return self.server.address
        dl = self.state.hb_deadline_s
        if dl is not None \
                and time.monotonic() - self.last_stream > dl:
            return None
        return self.peers.get(self.primary_index)


def _apply_replicated(state, op, now):
    """Apply one replicated op to standby state (caller holds the
    lock). The response is discarded — determinism comes from applying
    the SAME op sequence to the SAME starting snapshot; heartbeat
    leases land on the standby's own clock, which is exactly what its
    post-promotion monitor must judge by."""
    cmd = op.get("cmd")
    hid = op.get("host")
    hid = None if hid is None else int(hid)
    try:
        _dispatch(state, cmd, hid, op, now)
    except Exception:   # pragma: no cover - a poison op must not
        pass            # kill the stream; the state simply skips it


class CoordServer(object):
    """The rendezvous service: TCP + threads, stdlib only.

    One per pod — or, replicated, one GROUP per pod (see the module
    docstring): ``configure_replication(index, peers, standby=)``
    before :meth:`start` wires this member into a term-numbered
    primary/warm-standby group; :func:`replicated_group` builds a whole
    in-process group for tests and benches. ``snapshot_path=`` arms
    periodic on-disk state snapshots (reloaded on construction) so even
    a SOLO deployment survives a supervised restart with its in-flight
    rounds intact.

    Start in-process (tests, or the host-0 sidecar pattern) or
    standalone through ``tools/coordsvc.py``. ``port=0`` binds an
    ephemeral port — read it back from :attr:`address`.
    ``n_hosts=None`` starts in auto-size mode: the pod size is learned
    from the first hello that carries one (``tools/coordsvc.py
    --n-hosts auto``) — elastic group sizes without up-front config.

    ``hb_deadline_s`` arms heartbeat liveness: any host that ever said
    hello and then goes silent past the deadline is tombstoned by the
    monitor thread, exactly as if a peer had declared it lost — clients
    observe the tombstone on their next heartbeat/poll and fire their
    loss hooks. ``None`` disables the monitor (losses then come only
    from explicit ``mark_lost`` / gather deadlines, the FileCoordinator
    default). The SAME deadline judges the primary in a replicated
    group: a standby whose replication stream goes stale past it runs
    the promotion dance."""

    def __init__(self, n_hosts, port=0, host="127.0.0.1",
                 hb_deadline_s=None, snapshot_path=None,
                 snapshot_every_s=5.0, blob_max_bytes=64 * 1024 * 1024):
        self._state = _PodState(n_hosts, hb_deadline_s=hb_deadline_s)
        # legacy-mailbox ceiling (server config, not replicated state):
        # finite by default so a legacy-mode pod with an oversized scope
        # gets a NAMED refusal instead of silently growing this process
        # by n_hosts x scope. None disables.
        self._state.blob_max_bytes = None if blob_max_bytes is None \
            else int(blob_max_bytes)
        self._repl = None
        self._snapshot_path = snapshot_path
        self._snapshot_every_s = float(snapshot_every_s)
        if snapshot_path and os.path.exists(snapshot_path):
            try:
                with open(snapshot_path) as fh:
                    snap = json.load(fh)
                with self._state.lock:
                    self._state.load_snapshot(snap, time.monotonic())
                record_event("transport_snapshot_load",
                             seq=self._state.applied_seq,
                             term=self._state.term)
            except (OSError, ValueError):
                # a torn/unreadable snapshot must not block the
                # restart: the service comes up empty (the pre-snapshot
                # behavior) and the next period overwrites it
                record_event("transport_snapshot_corrupt",
                             path=str(snapshot_path))
        state = self._state
        server_self = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                # register the live connection: kill()/close() sever
                # every one of them, because a "dead" member that keeps
                # answering on long-lived sockets is exactly the stale
                # primary the chaos tests must reproduce
                with server_self._conns_lock:
                    server_self._conns.add(self.connection)
                try:
                    while not server_self._dead:
                        line = self.rfile.readline()
                        if not line:
                            return
                        try:
                            req = json.loads(line)
                            resp = _serve(server_self, state, req)
                        except Exception as e:   # malformed request
                            resp = {"error": "%s: %s"
                                    % (type(e).__name__, e)}
                        self.wfile.write(json.dumps(resp).encode()
                                         + b"\n")
                        self.wfile.flush()
                finally:
                    with server_self._conns_lock:
                        server_self._conns.discard(self.connection)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._conns = set()
        self._conns_lock = threading.Lock()
        self._server = _Server((host, port), _Handler)
        self.address = "%s:%d" % self._server.server_address[:2]
        self._threads = []
        self._closed = threading.Event()
        self._dead = False

    @property
    def state(self):
        """The live :class:`_PodState` — in-process introspection for
        tests and the host-0 sidecar (read under ``state.lock``)."""
        return self._state

    def configure_replication(self, index, peers, standby=False,
                              sync_timeout_s=2.0):
        """Wire this member into a replication group BEFORE start():
        ``peers`` is the ordered endpoint list (or {index: addr} map)
        of the WHOLE group — own entry included, skipped by ``index``.
        ``standby=True`` boots in standby role (waits for the stream);
        a member booted primary still probes its peers first and defers
        to a higher-term incumbent (the restarted ex-primary path)."""
        self._repl = _Replication(self, index, peers, standby,
                                  sync_timeout_s=sync_timeout_s)
        return self

    def _replicate_locked(self, op):
        """Primary-side: take the next stream seq for ``op`` and
        publish it to the senders. Caller holds ``state.lock``. Returns
        the seq (to sync-wait on), or None when not replicating."""
        if self._repl is None or self._state.role != "primary":
            return None
        self._state.applied_seq += 1
        seq = self._state.applied_seq
        self._repl.publish_locked(seq, op)
        return seq

    def _scan_and_replicate_locked(self, now):
        """Heartbeat scan + synthetic-tombstone replication, the ONE
        home for both fencing paths (the monitor thread and the
        per-request piggyback): monitor tombstones are mutations with
        no client op behind them, so the stream carries them as
        synthetic mark_lost ops. Caller holds ``state.lock``; returns
        the newly fenced ids."""
        newly = self._state._scan_heartbeats(now)
        for hid in newly:
            self._replicate_locked(
                {"cmd": "mark_lost", "host": hid,
                 "reason": self._state.lost.get(hid,
                                                "missed heartbeat")})
        return newly

    def start(self):
        t = threading.Thread(target=self._server.serve_forever,
                             daemon=True, name="paddle_tpu-coordsvc")
        t.start()
        self._threads.append(t)
        if self._state.hb_deadline_s is not None:
            m = threading.Thread(target=self._monitor, daemon=True,
                                 name="paddle_tpu-coordsvc-hb")
            m.start()
            self._threads.append(m)
        if self._repl is not None:
            self._repl.start()
        if self._snapshot_path:
            s = threading.Thread(target=self._snapshot_loop, daemon=True,
                                 name="paddle_tpu-coordsvc-snap")
            s.start()
            self._threads.append(s)
        return self

    def _monitor(self):
        period = max(0.01, self._state.hb_deadline_s / 4.0)
        while not self._closed.wait(period):
            with self._state.lock:
                if self._state.role != "primary":
                    continue   # only the primary judges host liveness
                newly = self._scan_and_replicate_locked(time.monotonic())
            for hid in newly:
                record_event("hb_lost", host_lost=hid)

    def _snapshot_loop(self):
        while not self._closed.wait(self._snapshot_every_s):
            self.save_snapshot()

    def save_snapshot(self):
        """Persist the full state atomically (temp + replace). A no-op
        without ``snapshot_path``; called periodically and on close."""
        if not self._snapshot_path:
            return None
        with self._state.lock:
            blob = json.dumps(self._state.to_snapshot())
        tmp = "%s.tmp.%d" % (self._snapshot_path, os.getpid())
        try:
            with open(tmp, "w") as fh:
                fh.write(blob)
            os.replace(tmp, self._snapshot_path)
        except OSError:   # pragma: no cover - disk trouble
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        return self._snapshot_path

    def _sever_connections(self):
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def close(self):
        if self._dead:
            return
        self._dead = True
        self._closed.set()
        if self._repl is not None:
            self._repl.stop()
        self.save_snapshot()
        self._server.shutdown()
        self._sever_connections()
        self._server.server_close()
        for t in self._threads:
            t.join(timeout=5.0)

    def kill(self):
        """Abrupt in-process death for chaos tests and benches: stop
        serving NOW — no final snapshot, no graceful joins, every live
        connection severed — so peers and clients see exactly what a
        SIGKILL leaves behind."""
        if self._dead:
            return
        self._dead = True
        self._closed.set()
        if self._repl is not None:
            self._repl.stop(join=False)
        self._server.shutdown()
        self._sever_connections()
        self._server.server_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def replicated_group(n_hosts, n_members=2, host="127.0.0.1",
                     hb_deadline_s=1.0, snapshot_paths=None,
                     sync_timeout_s=2.0):
    """Build + wire + start a whole in-process replication group:
    member 0 boots primary, the rest warm standbys, all sharing the
    ordered endpoint list. Returns the server list (same order as the
    endpoints clients should dial). Tests and bench_micro ride this;
    production deploys one ``coordsvc --peers ... --repl-index i``
    per member instead."""
    servers = [CoordServer(n_hosts, host=host,
                           hb_deadline_s=hb_deadline_s,
                           snapshot_path=None if snapshot_paths is None
                           else snapshot_paths[i])
               for i in range(n_members)]
    addrs = [s.address for s in servers]
    for i, s in enumerate(servers):
        s.configure_replication(i, addrs, standby=(i != 0),
                                sync_timeout_s=sync_timeout_s)
    for s in servers:
        s.start()
    return servers


def _serve(server, state, req):
    """Dispatch one request against the pod state. Every client op is
    idempotent so a client may blindly re-send after a reconnect (or a
    failover — the promoted standby holds the replicated state)."""
    cmd = req.get("cmd")
    now = time.monotonic()
    if cmd in _REPL_CMDS:
        repl = server._repl
        if repl is None:
            return {"error": "replication not configured on this member"}
        with state.lock:
            return repl.handle_locked(state, req, now)
    if cmd == "status":
        return _serve_status(server, state, now)
    if cmd == "time":
        # the obs clock-offset probe (obs.probe_clock_offset): the
        # server's wall clock, answered statelessly so it works before
        # the first sized hello and on standbys alike — tracing
        # alignment must not depend on group membership
        return {"ok": True, "wall": time.time()}
    hid = req.get("host")
    hid = None if hid is None else int(hid)
    wait_seq = None
    with state.lock:
        if state.role != "primary":
            # term-fenced redirect: a standby (or a demoted ex-primary)
            # serves NOTHING mutable — the client fails over on the
            # not_primary marker, or rejects a stale term outright
            hint = None if server._repl is None \
                else server._repl.primary_hint()
            return {"not_primary": True, "role": state.role,
                    "term": state.term, "primary": hint,
                    "error": "not primary (standby at term %d) — dial "
                    "the primary" % state.term}
        # both guards read state.n_hosts INSIDE the lock: in auto-size
        # mode a non-hello op racing the first sized hello must see
        # one consistent value — a torn read could skip the range
        # check and land exactly the phantom state it exists to block
        if hid is not None and state.n_hosts is not None \
                and not 0 <= hid < state.n_hosts:
            # an off-by-one host id must fail loudly, not land phantom
            # contributions in rounds or phantom tombstones
            return {"error": "host id %d out of range for a %d-host "
                    "pod" % (hid, state.n_hosts)}
        if state.n_hosts is None and cmd != "hello":
            # auto-size mode before the first sized hello: nothing
            # else can be range-checked or frozen yet
            return {"error": "pod size not learned yet — the first "
                    "hello must carry n_hosts (auto-size mode)"}
        # the heartbeat monitor owns proactive scans, but piggybacking
        # one on every request keeps detection sharp under load (and
        # makes the deadline hold even on a paused monitor thread)
        server._scan_and_replicate_locked(now)
        resp = _dispatch(state, cmd, hid, req, now)
        if "lost" in resp:
            # every lost map ships with its version: the client drops
            # any map older than one it already applied, so a response
            # processed late cannot resurrect a cleared tombstone
            resp["lost_v"] = state.lost_version
        if cmd in _MUTATING_CMDS and "error" not in resp \
                and "fenced" not in resp:
            seq = server._replicate_locked(dict(req, cmd=cmd))
            if seq is not None and cmd in _SYNC_CMDS:
                wait_seq = seq
        # the term rides EVERY response: the client's staleness fence
        resp["term"] = state.term
    if wait_seq is not None:
        # sync replication happens OUTSIDE the lock: a slow standby
        # must never serialize the whole service behind its socket
        server._repl.wait_replicated(wait_seq,
                                     server._repl.sync_timeout_s)
    return resp


def _serve_status(server, state, now):
    """The ``status`` probe — served by EVERY role (it is how standbys
    probe each other during the promotion dance, how coordsvc --status
    answers operators, and how a restarted ex-primary discovers the
    incumbent)."""
    repl = server._repl
    with state.lock:
        resp = {"ok": True, "role": state.role, "term": state.term,
                "seq": state.applied_seq, "n_hosts": state.n_hosts,
                "hb_deadline_s": state.hb_deadline_s,
                "address": server.address}
        if repl is not None:
            resp["index"] = repl.index
            resp["peers"] = {str(i): a
                             for i, a in sorted(repl.peers.items())}
            resp["primary"] = repl.primary_hint()
            if state.role == "primary":
                with repl.cond:
                    resp["repl_acked"] = {str(p): repl.acked.get(p, 0)
                                          for p in repl.peers}
                    resp["repl_in_sync"] = {str(p): bool(
                        repl.in_sync.get(p)) for p in repl.peers}
                    resp["repl_lag"] = max(
                        (state.applied_seq - repl.acked.get(p, 0)
                         for p in repl.peers if repl.in_sync.get(p)),
                        default=0)
            else:
                resp["stream_age_s"] = round(
                    now - repl.last_stream, 6)
    return resp


def _dispatch(state, cmd, hid, req, now):
    """The op table — caller holds ``state.lock``."""
    if cmd == "hello":
        if state.n_hosts is None:
            # auto-size: the first sized hello fixes the pod size for
            # the service's lifetime; later hellos must agree. The
            # validation runs BEFORE the commit — an error return must
            # not have the side effect of pinning a bogus size
            if req.get("n_hosts") is None:
                return {"error": "pod size not learned yet — this "
                        "hello must carry n_hosts (auto-size mode)"}
            want = int(req["n_hosts"])
            if want < 1:
                return {"error": "n_hosts must be >= 1, got %d" % want}
            if hid is not None and not 0 <= hid < want:
                return {"error": "host id %d out of range for a "
                        "%d-host pod" % (hid, want)}
            state.n_hosts = want
        if int(req.get("n_hosts", state.n_hosts)) != state.n_hosts:
            resized = (" — the group was RESIZED (v%d): relaunch this "
                       "member with the current size"
                       % state.resize_version) \
                if state.resize_version else ""
            return {"error": "pod size mismatch: server has %d "
                    "hosts, client expects %s%s"
                    % (state.n_hosts, req.get("n_hosts"), resized)}
        if hid is not None and req.get("lease"):
            # only heartbeating clients take a liveness lease: a
            # passive observer (heartbeat=False) that registered
            # one would be tombstoned the moment it went stale
            state.hb[hid] = now
        return {"ok": True, "n_hosts": state.n_hosts,
                "lost": dict(state.lost)}
    if cmd == "hb":
        if hid is not None:
            state.hb[hid] = now
        return {"ok": True, "lost": dict(state.lost)}
    if cmd == "lost":
        return {"lost": dict(state.lost)}
    if cmd == "mark_lost":
        state._mark_lost(hid, req.get("reason", "declared lost"))
        return {"ok": True, "lost": dict(state.lost)}
    if cmd == "announce_join":
        if hid not in state.lost:
            return {"error": "host %d is not fenced — only a lost "
                    "host announces a rejoin" % hid}
        state.joins[hid] = int(req.get("nonce", 0))
        return {"ok": True}
    if cmd == "pending_joins":
        return {"joins": dict(state.joins)}
    if cmd == "unfence":
        if state.lost.pop(hid, None) is not None:
            state.lost_version += 1
        state.joins.pop(hid, None)
        # the un-fenced host re-enters liveness with a fresh lease —
        # without this its pre-fence stale heartbeat would re-fence
        # it on the very next monitor scan
        if hid in state.hb:
            state.hb[hid] = now
        # the response CARRIES the post-unfence lost map: the caller's
        # client applies its (bumped) version before the coordinator
        # forgets the host, so any straggling pre-unfence callback is
        # dropped by the version guard instead of resurrecting the loss
        return {"ok": True, "lost": dict(state.lost)}
    if cmd == "put":
        name = req["name"]
        if hid in state.lost:
            return {"fenced": state.lost[hid], "lost": dict(state.lost)}
        r = state.rounds.setdefault(
            name, {"values": {}, "tokens": {}, "done": None,
                   "acks": set()})
        token = req.get("token")
        if hid in r["values"]:
            if r["tokens"].get(hid) == token and token is not None:
                # the same client re-sending after a reconnect (or a
                # FAILOVER onto the promoted standby): idempotent,
                # keyed by (name, host_id, token)
                return {"ok": True, "resent": True}
            return {"error": "host %d already contributed to round "
                    "%r — collective names must be unique per round"
                    % (hid, name)}
        if r["done"] is not None:
            # frozen without us: we were fenced when the snapshot
            # was taken — arriving now must not mutate it
            return {"fenced": state.lost.get(
                hid, "round %r froze without host %d" % (name, hid)),
                "lost": dict(state.lost)}
        r["values"][hid] = req.get("value")
        r["tokens"][hid] = token
        state._freeze_if_complete(name)
        return {"ok": True}
    if cmd == "poll":
        name = req["name"]
        r = state.rounds.get(name)
        if hid in state.lost and (r is None or r["done"] is None
                                  or hid not in r["done"]):
            return {"fenced": state.lost[hid], "lost": dict(state.lost)}
        if r is None:
            return {"error": "round %r unknown — poll follows put"
                    % name}
        state._freeze_if_complete(name)
        if r["done"] is None:
            waiting = [i for i in range(state.n_hosts)
                       if i not in state.lost
                       and i not in r["values"]]
            return {"waiting": waiting, "lost": dict(state.lost)}
        return {"done": r["done"],
                "values": {str(i): r["values"][i] for i in r["done"]},
                "lost": dict(state.lost)}
    if cmd == "ack":
        name = req["name"]
        r = state.rounds.get(name)
        if r is not None and r["done"] is not None:
            r["acks"].add(hid)
            if r["acks"] >= set(r["done"]):
                # last one out cleans up (File/LocalCoordinator
                # parity) — the rounds table stays bounded
                state.rounds.pop(name, None)
        return {"ok": True}
    if cmd == "put_info":
        # member-published blob (last write wins, idempotent): how a
        # serving replica advertises its HTTP address + generation so
        # the router never needs static fleet configuration
        if hid is None:
            return {"error": "put_info needs a host id"}
        state.info[hid] = req.get("info")
        return {"ok": True}
    if cmd == "put_blob":
        # buddy-checkpoint mailbox write: ONE generation per owner
        # (bounded memory), generation-fenced so a delayed/replayed
        # put can never rewind the mailbox below what a restore may
        # already have adopted. Primary-replicated (_SYNC_CMDS) and
        # snapshot-covered: a coordinator failover mid-window keeps
        # every acked snapshot.
        if hid is None:
            return {"error": "put_blob needs a host id"}
        if hid in state.lost:
            return {"fenced": state.lost[hid], "lost": dict(state.lost)}
        try:
            gen = int(req["gen"])
            buddy = int(req["buddy"])
        except (KeyError, TypeError, ValueError):
            return {"error": "put_blob needs integer gen and buddy"}
        nb = _blob_nbytes(req.get("blob"))
        if state.blob_max_bytes is not None \
                and nb > state.blob_max_bytes:
            # named refusal the client maps to BlobTooLargeError: a
            # legacy-mode pod whose scope outgrew the coordinator gets
            # a typed error (and falls back to the disk tier), never a
            # silent coordinator OOM. The p2p tier has no such ceiling
            # — payloads live in peer mailboxes.
            return {"error": "blob_max_bytes exceeded: put_blob of %d "
                    "bytes for host %d is over the coordinator's %d-"
                    "byte ceiling — use the p2p mailbox tier for "
                    "scopes this size" % (nb, hid,
                                          state.blob_max_bytes)}
        prev = state.blobs.get(hid)
        if req.get("reset"):
            # post-disk-restore re-seed: the pod legitimately rewound
            # below the mailbox generation (and a poison-batch replay
            # may change the trajectory, so even an equal-gen blob is
            # from the WRONG history) — force-overwrite, bypassing the
            # rewind fence
            state.blobs[hid] = {"gen": gen, "buddy": buddy,
                                "blob": req.get("blob")}
            _record_coord_resident(state)
            return {"ok": True, "reset": True}
        if prev is not None and gen < int(prev["gen"]):
            return {"error": "put_blob generation rewind: host %d is "
                    "at gen %d on the server, refused gen %d"
                    % (hid, int(prev["gen"]), gen)}
        if prev is not None and gen == int(prev["gen"]):
            # same client re-sending after a reconnect or a failover
            # onto the promoted standby: idempotent, keyed by gen
            return {"ok": True, "resent": True}
        state.blobs[hid] = {"gen": gen, "buddy": buddy,
                            "blob": req.get("blob")}
        _record_coord_resident(state)
        return {"ok": True}
    if cmd == "get_blob":
        # read-only mailbox fetch; meta_only skips the payload so the
        # restore election can poll generations cheaply. No fencing:
        # a fenced survivor reading its own (or a dead peer's) last
        # snapshot is exactly the restore path.
        try:
            owner = int(req["owner"])
        except (KeyError, TypeError, ValueError):
            return {"error": "get_blob needs an integer owner"}
        rec = state.blobs.get(owner)
        if rec is None:
            return {"miss": True}
        resp = {"gen": int(rec["gen"]), "buddy": int(rec["buddy"])}
        if not req.get("meta_only"):
            resp["blob"] = rec["blob"]
        return resp
    if cmd == "mailbox_hello":
        # p2p buddy tier: a host registers its MailboxServer endpoint
        # so restore-time peers can resolve host-to-host pulls.
        # Primary-replicated and snapshot-covered — the address book
        # must survive coordinator failover just like the metadata.
        if hid is None:
            return {"error": "mailbox_hello needs a host id"}
        addr = req.get("addr")
        if not addr:
            return {"error": "mailbox_hello needs an addr"}
        state.mailbox_addrs[hid] = str(addr)
        return {"ok": True}
    if cmd == "put_buddy_meta":
        # p2p buddy tier COMMIT: after the ring buddy's mailbox acked
        # the deposited payload, the sender publishes this metadata row
        # — {gen, buddy, digest, nbytes}, a few hundred bytes per host
        # regardless of scope size. Same generation fence as put_blob:
        # a delayed/replayed commit can never rewind the row below
        # what a restore may already have elected. Replicated
        # (_SYNC_CMDS) and snapshot-covered.
        if hid is None:
            return {"error": "put_buddy_meta needs a host id"}
        if hid in state.lost:
            return {"fenced": state.lost[hid], "lost": dict(state.lost)}
        try:
            gen = int(req["gen"])
            buddy = int(req["buddy"])
        except (KeyError, TypeError, ValueError):
            return {"error": "put_buddy_meta needs integer gen and "
                    "buddy"}
        row = {"gen": gen, "buddy": buddy,
               "digest": req.get("digest"),
               "nbytes": int(req.get("nbytes", 0))}
        prev = state.buddy_meta.get(hid)
        if req.get("reset"):
            state.buddy_meta[hid] = row
            _record_coord_resident(state)
            return {"ok": True, "reset": True}
        if prev is not None and gen < int(prev["gen"]):
            return {"error": "put_buddy_meta generation rewind: host "
                    "%d is at gen %d on the server, refused gen %d"
                    % (hid, int(prev["gen"]), gen)}
        if prev is not None and gen == int(prev["gen"]):
            return {"ok": True, "resent": True}
        state.buddy_meta[hid] = row
        _record_coord_resident(state)
        return {"ok": True}
    if cmd == "buddy_meta":
        # read-only metadata fetch for restore planning — one owner's
        # row, or the whole table + mailbox address book when no owner
        # is named. No fencing, same reasoning as get_blob.
        owner = req.get("owner")
        if owner is not None:
            rec = state.buddy_meta.get(int(owner))
            if rec is None:
                return {"miss": True}
            resp = dict(rec)
            resp["addr"] = state.mailbox_addrs.get(int(rec["buddy"]))
            return resp
        return {"meta": {str(h): dict(r)
                         for h, r in state.buddy_meta.items()},
                "addrs": {str(h): a
                          for h, a in state.mailbox_addrs.items()}}
    if cmd == "members":
        # one poll answers the whole routing question: who is
        # registered (info), who is fenced (lost — versioned by the
        # caller in _serve), and how stale each liveness lease is.
        # The server's deadline ships too, so clients can judge a
        # lease "live-looking" by the SAME bound the monitor fences by
        return {"n_hosts": state.n_hosts,
                "resize_v": state.resize_version,
                "hb_deadline_s": state.hb_deadline_s,
                "hb_age": {str(h): round(now - t, 6)
                           for h, t in state.hb.items()},
                "info": {str(h): v for h, v in state.info.items()},
                "lost": dict(state.lost)}
    if cmd == "resize":
        # DYNAMIC GROUP RESIZE: grow/shrink n_hosts at a round
        # boundary. Grown slots are born FENCED ("resized: awaiting
        # join") so in-flight gathers never wait for a member that has
        # not joined — the new member's start finds itself fenced and
        # takes the ordinary announce/admit/join path. A shrink only
        # removes TOP ids whose members are already fenced or hold no
        # live-looking lease (drain first). Primary-replicated
        # (_SYNC_CMDS) and snapshot-covered, so the resized size
        # survives failover and restart.
        try:
            want = int(req["n_hosts"])
        except (KeyError, TypeError, ValueError):
            return {"error": "resize needs an integer n_hosts"}
        if want < 1:
            return {"error": "resize: n_hosts must be >= 1, got %d"
                    % want}
        open_rounds = sorted(n for n, r in state.rounds.items()
                             if r["done"] is None)
        if open_rounds:
            return {"error": "resize refused mid-round: %d gather "
                    "round(s) in flight (%s) — retry at a round "
                    "boundary" % (len(open_rounds), open_rounds[:3])}
        if want == state.n_hosts:
            return {"ok": True, "n_hosts": want,
                    "resize_v": state.resize_version,
                    "lost": dict(state.lost)}
        if want < state.n_hosts:
            dl = state.hb_deadline_s
            live = [h for h in range(want, state.n_hosts)
                    if h not in state.lost and h in state.hb
                    and (dl is None or now - state.hb[h] <= dl)]
            if live:
                return {"error": "resize refused: host(s) %s hold a "
                        "live lease — drain/fence them before "
                        "shrinking past their ids" % live}
            for h in range(want, state.n_hosts):
                state.lost.pop(h, None)
                state.joins.pop(h, None)
                state.hb.pop(h, None)
                state.info.pop(h, None)
                state.blobs.pop(h, None)
                state.buddy_meta.pop(h, None)
                state.mailbox_addrs.pop(h, None)
            state.lost_version += 1
        else:
            for h in range(state.n_hosts, want):
                state._mark_lost(h, GROW_FENCE_REASON)
        state.n_hosts = want
        state.resize_version += 1
        return {"ok": True, "n_hosts": want,
                "resize_v": state.resize_version,
                "lost": dict(state.lost)}
    return {"error": "unknown cmd %r" % cmd}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

def _parse_endpoints(address):
    """Accepts one "host:port", a comma-joined list of them, a
    ("host", port) pair, or a list/tuple of endpoint strings — the
    replicated-group client shape. Returns [(host, port), ...] in
    priority order (primary first, by convention)."""
    if isinstance(address, (tuple, list)):
        items = list(address)
        # a 2-tuple whose second element is a (numeric) port is the
        # classic (host, port) pair — judged by the PORT, not by a ":"
        # in the host, so IPv6 literals like ("::1", 9000) keep working
        if len(items) == 2 and isinstance(items[0], str) and (
                isinstance(items[1], int)
                or (isinstance(items[1], str) and items[1].isdigit())):
            return [(items[0], int(items[1]))]
        out = []
        for it in items:
            out.extend(_parse_endpoints(it))
        return out
    out = []
    for part in str(address).split(","):
        part = part.strip()
        if part:
            out.append(_split_addr(part))
    if not out:
        raise ValueError("no endpoint in address %r" % (address,))
    return out


class CoordClient(object):
    """Request/response client with transparent reconnect AND failover.

    One per (process, host_id). All requests serialize on one socket
    under a lock — the heartbeat thread shares it, so ordering is
    strict and the server never sees interleaved lines. A send/recv
    failure tears the socket down and retries through ``retry_policy``
    (connect + re-send; server ops are idempotent), recording a
    ``transport_reconnect`` event per re-dial so
    ``transport_reconnects_total`` counts real network pain.

    ``address`` may be a LIST of endpoints (a replication group, in
    index order): on socket failure — or on a standby's ``not_primary``
    redirect — the client rotates to the next endpoint inside the same
    retry budget, so a primary SIGKILL costs one failover, not an
    error. Every response's ``term`` is tracked: a response carrying a
    LOWER term than one already observed comes from a stale ex-primary
    and is REFUSED (``transport_stale_primary`` event + rotate) — the
    client-side half of the term fence. Successful endpoint switches
    count in ``transport_failovers_total``; the observed term rides the
    ``transport_term`` gauge.

    ``hb_interval_s`` starts the daemon heartbeat on :meth:`start_heartbeat`
    callers; each beat refreshes this host's liveness lease and records
    the ``transport_hb_lag`` gauge — seconds the cadence is running
    late (0 when healthy). The latest ``lost`` map from any response is
    kept on :attr:`last_lost` for the owner to diff against."""

    def __init__(self, address, host_id=None, retry_policy=None,
                 connect_timeout_s=5.0, io_timeout_s=30.0):
        self._endpoints = _parse_endpoints(address)
        self._ep_i = 0
        self._ep_last_ok = None
        self.host_id = None if host_id is None else int(host_id)
        # the default budget rides out a SUPERVISED RESTART of the
        # rendezvous service (~5-10s of backoff) — and therefore also a
        # standby PROMOTION, which completes within the group's
        # heartbeat deadline — not just a dropped connection; pass a
        # bigger retry_policy for slower orchestrators
        self._policy = retry_policy or RetryPolicy(
            max_attempts=9, base_delay_s=0.1, max_delay_s=2.0)
        self._connect_timeout_s = float(connect_timeout_s)
        # every server op answers immediately (no server-side blocking),
        # so a bounded read is purely a hang guard: a wedged service
        # must not pin the request lock — and with it the heartbeat AND
        # gather threads — forever
        self._io_timeout_s = None if io_timeout_s is None \
            else float(io_timeout_s)
        self._lock = threading.Lock()
        self._sock = None
        self._rfile = None
        self._closed = False
        self._hb_thread = None
        self._hb_stop = threading.Event()
        self.last_lost = {}
        self._lost_cb = None
        # ordering guard for the lost map: responses finish their
        # roundtrip under _lock but are PROCESSED after releasing it,
        # so a slow thread could apply a stale map after a newer one
        # (resurrecting a cleared tombstone). The server versions every
        # map; we only ever apply forward.
        self._lost_lock = threading.Lock()
        self._lost_v = -1
        # the term fence: the highest replication term any response
        # carried. Guarded by _lost_lock (same tiny critical sections).
        self.term_seen = 0
        # instantaneous heartbeat-cadence lag, updated every beat (the
        # recorded gauge EVENTS are throttled — see _hb_loop)
        self.hb_lag_s = 0.0

    @property
    def _addr(self):
        return self._endpoints[self._ep_i]

    # -- wire --------------------------------------------------------------
    def _connect_locked(self):
        sock = socket.create_connection(
            self._addr, timeout=self._connect_timeout_s)
        sock.settimeout(self._io_timeout_s)
        self._sock = sock
        self._rfile = sock.makefile("rb")

    def _teardown_locked(self):
        for closer in (self._rfile, self._sock):
            try:
                if closer is not None:
                    closer.close()
            except OSError:
                pass
        self._sock = self._rfile = None

    def _roundtrip_locked(self, payload):
        if self._sock is None:
            self._connect_locked()
        # chaos surface: a raise here is caught by request()'s socket-
        # error handler (reconnect/rotate/backoff); DROP models a
        # message lost in flight without waiting out the read timeout
        out = faultinject.hit("transport.send", payload,
                              host=self.host_id)
        if out is faultinject.DROP:
            self._teardown_locked()
            raise ConnectionError("transport.send: dropped by failpoint")
        self._sock.sendall(out)
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("coordination service closed the "
                                  "connection")
        return json.loads(line)

    def _rotate_locked(self, hint=None):
        """Advance to the next endpoint (or jump to the ``primary``
        hint a standby handed back). A single-endpoint client only
        reconnects — there is nowhere to fail over to."""
        if hint:
            try:
                hp = _split_addr(hint)
            except (ValueError, TypeError):
                hp = None
            if hp is not None:
                if hp not in self._endpoints:
                    self._endpoints.append(hp)
                self._ep_i = self._endpoints.index(hp)
                return
        if len(self._endpoints) > 1:
            self._ep_i = (self._ep_i + 1) % len(self._endpoints)

    def _screen_response(self, resp):
        """Term fence + failover redirect. Returns None to ACCEPT the
        response, or a ("kind", exception) pair describing why it must
        be retried on another endpoint: kind "stale" (an ex-primary's
        lower term, refused) or "standby" (a not-yet-promoted member's
        redirect — wait and re-probe)."""
        term = resp.get("term")
        if term is not None:
            term = int(term)
            with self._lost_lock:
                seen = self.term_seen
                stale = term < seen
                if term > seen:
                    self.term_seen = term
            if stale:
                # a response from a lower term than one we already
                # observed: a stale ex-primary woke up. Refuse it — the
                # promoted member holds the truth.
                record_event("transport_stale_primary",
                             host=self.host_id, term=term, seen=seen)
                with self._lock:
                    self._teardown_locked()
                    self._rotate_locked()
                return ("stale", ConnectionError(
                    "stale-term response (term %d < observed %d) — "
                    "refused and failing over" % (term, seen)))
            if term > seen:
                record_event("transport_term", host=self.host_id,
                             term=term)
        if resp.get("not_primary"):
            hint = resp.get("primary")
            with self._lock:
                self._teardown_locked()
                self._rotate_locked(hint)
            return ("standby", ConnectionError(
                "endpoint is a standby (term %s) — failing over"
                % resp.get("term")))
        return None

    # a standby's redirect means the group EXISTS but is mid-promotion:
    # the wait is bounded by this wall clock (generous vs any sane
    # hb_deadline_s) at a tight cadence, NOT by the reconnect attempt
    # budget at full backoff — burning attempts against a known-alive
    # group would spend the whole budget before promotion lands
    _STANDBY_WAIT_S = 30.0
    _STANDBY_POLL_S = 0.05

    def request(self, req):
        """One request/response round trip; reconnects, re-sends and
        FAILS OVER across the endpoint list on transient failure
        (requests are idempotent server-side; stale-term responses are
        refused; a mid-promotion group is waited out). Raises
        :class:`TransportError` once the retry budget is spent."""
        payload = json.dumps(req).encode() + b"\n"
        last = None
        attempt = 0
        standby_deadline = None
        while True:
            resp = None
            socket_err = False
            with self._lock:
                if self._closed:
                    raise TransportError("client is closed")
                try:
                    resp = self._roundtrip_locked(payload)
                except (OSError, ValueError) as e:
                    # ValueError: a torn JSON line from a half-closed
                    # socket — same remedy as any socket error
                    last = e
                    socket_err = True
                    self._teardown_locked()
            if resp is not None:
                verdict = self._screen_response(resp)
                if verdict is None:
                    ep = self._ep_i
                    if self._ep_last_ok is not None \
                            and self._ep_last_ok != ep:
                        # the first accepted answer from a NEW endpoint
                        # after talking to another: one failover landed
                        record_event("transport_failover",
                                     host=self.host_id,
                                     endpoint="%s:%d" % self._addr)
                    self._ep_last_ok = ep
                    return resp
                kind, last = verdict
                if kind == "standby":
                    now = time.monotonic()
                    if standby_deadline is None:
                        standby_deadline = now + self._STANDBY_WAIT_S
                    if now >= standby_deadline:
                        break
                    self._policy.sleep(self._STANDBY_POLL_S)
                    continue
            if socket_err and standby_deadline is not None \
                    and time.monotonic() < standby_deadline:
                # a live standby already answered this request: the
                # group EXISTS, we are only waiting out its promotion.
                # A refused connection (the dead ex-primary) must not
                # burn the bounded attempt budget with growing backoff
                # — rotate and keep the tight promotion-wait cadence.
                with self._lock:
                    self._rotate_locked()
                self._policy.sleep(self._STANDBY_POLL_S)
                continue
            attempt += 1
            if attempt >= self._policy.max_attempts:
                break
            delay = self._policy.delay_s(attempt - 1)
            if socket_err:
                with self._lock:
                    self._rotate_locked()
                record_event("transport_reconnect", attempt=attempt,
                             error=type(last).__name__, backoff_s=delay,
                             host=self.host_id)
            self._policy.sleep(delay)
        raise TransportError(
            "coordination service unreachable at %s after %d attempts; "
            "last error: %r"
            % (["%s:%d" % ep for ep in self._endpoints],
               self._policy.max_attempts, last))

    def call(self, cmd, **fields):
        """request() + server-error unwrapping. Returns the response
        dict; a server-side ``error`` raises RuntimeError (the caller
        maps it onto the Coordinator error taxonomy). Tracks the most
        recent ``lost`` map for the owner's loss observation."""
        req = dict(fields, cmd=cmd)
        if self.host_id is not None and "host" not in req:
            req["host"] = self.host_id
        resp = self.request(req)
        if "lost" in resp:
            parsed = {int(k): v for k, v in resp["lost"].items()}
            version = int(resp.get("lost_v", 0))
            with self._lost_lock:
                if version >= self._lost_v:
                    self._lost_v = version
                    self.last_lost = parsed
                # the callback always sees the NEWEST map known to this
                # client (never a stale response's own) AND its version
                # — the consumer re-checks it under ITS lock, because
                # this invocation happens outside ours and a delayed
                # thread could otherwise deliver a pre-unfence map
                # after the owner already readmitted the host
                current = dict(self.last_lost)
                current_v = self._lost_v
            cb = self._lost_cb
            if cb is not None:
                cb(current, current_v)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    # -- heartbeat ---------------------------------------------------------
    def start_heartbeat(self, interval_s=_DEFAULT_HB_INTERVAL_S,
                        on_lost=None):
        """Say hello (registers this host's liveness lease) and start
        the daemon heartbeat. ``on_lost(lost_map)`` fires on every
        response that carries a lost map — the SocketCoordinator hangs
        its loss observation here so tombstones written by the server's
        deadline monitor reach the survivors' hooks without any gather
        in flight."""
        self._lost_cb = on_lost
        self._hb_interval_s = float(interval_s)
        self.call("hello", lease=True)
        t = threading.Thread(target=self._hb_loop, daemon=True,
                             name="paddle_tpu-hb-%s" % self.host_id)
        self._hb_thread = t
        t.start()
        return self

    def _hb_loop(self):
        last_beat = time.monotonic()
        last_recorded = 0.0
        beats = 0
        while not self._hb_stop.wait(self._hb_interval_s):
            try:
                # DROP loses the beat silently; an injected raise is
                # swallowed like any transport failure — either way the
                # server-side lease ages until the deadline monitor
                # declares this host lost
                if faultinject.hit("coordination.hb",
                                   host=self.host_id) is faultinject.DROP:
                    continue
                self.call("hb")
            except (TransportError, RuntimeError, ConnectionError):
                # the reconnect events already counted the pain; the
                # lease simply ages until the server or network heals
                continue
            now = time.monotonic()
            lag = max(0.0, (now - last_beat) - self._hb_interval_s)
            last_beat = now
            self.hb_lag_s = lag
            beats += 1
            # the gauge event is THROTTLED: the event log is a bounded
            # deque shared with the recovery history, and an unthrotted
            # 2 Hz stream would evict everything else within the hour.
            # Record when the cadence actually slipped (the signal) or
            # every ~60s as a keepalive so the gauge stays fresh; the
            # instantaneous value is always on .hb_lag_s.
            keepalive = max(1, int(60.0 / max(self._hb_interval_s,
                                              1e-3)))
            if lag > self._hb_interval_s or lag > last_recorded * 2 \
                    or beats % keepalive == 0:
                last_recorded = lag
                record_event("transport_hb_lag", host=self.host_id,
                             lag_s=lag)

    def close(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        with self._lock:
            self._closed = True
            self._teardown_locked()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# p2p buddy mailbox endpoint (one per host)
# ---------------------------------------------------------------------------

def mailbox_request(address, req, timeout_s=5.0):
    """One-shot newline-JSON request against a peer's MailboxServer.
    Raises ConnectionError on any wire failure — the buddy tier maps
    every raise to its typed fallbacks, never a hang (the socket
    timeout bounds the wait)."""
    try:
        with socket.create_connection(_split_addr(address),
                                      timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            s.sendall(json.dumps(req).encode() + b"\n")
            line = s.makefile("rb").readline()
    except OSError as e:
        raise ConnectionError(
            "mailbox at %s unreachable: %s" % (address, e))
    if not line:
        raise ConnectionError(
            "mailbox at %s closed the connection mid-request"
            % (address,))
    try:
        return json.loads(line)
    except ValueError as e:
        raise ConnectionError(
            "mailbox at %s sent a torn response: %s" % (address, e))


class MailboxServer(object):
    """One host's p2p buddy-mailbox endpoint: a tiny ThreadingTCPServer
    on the CoordServer newline-JSON wire, serving deposits into and
    fetches out of a :class:`buddy.BuddyMailbox` that lives in THIS
    host's RAM. The coordinator never sees a payload — only the
    metadata row the sender commits after the deposit is acked here.

    Ops (one JSON line in, one out):
      mb_deposit {owner, payload}   -> the mailbox's ack/refusal dict
      mb_fetch   {owner}            -> {gen, digest, blob} |
                                       {miss: true} | {refused: ...}
      mb_status  {}                 -> {owners: {o: meta},
                                       resident_bytes}

    ``port=0`` binds an ephemeral port — read :attr:`address` back and
    register it with the coordinator via ``mailbox_hello``."""

    def __init__(self, mailbox, host="127.0.0.1", port=0):
        self.mailbox = mailbox
        self._dead = False
        server_self = self

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while not server_self._dead:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        req = json.loads(line)
                        resp = server_self._serve(req)
                    except Exception as e:   # malformed request
                        resp = {"error": "%s: %s"
                                % (type(e).__name__, e)}
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, int(port)), _Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="paddle-tpu-mailbox", daemon=True)
        self._thread.start()

    @property
    def address(self):
        h, p = self._server.server_address[:2]
        return "%s:%d" % (h, p)

    def _serve(self, req):
        cmd = req.get("cmd")
        if cmd == "mb_deposit":
            return self.mailbox.deposit(int(req["owner"]),
                                        req["payload"])
        if cmd == "mb_fetch":
            try:
                return self.mailbox.reconstruct(int(req["owner"]))
            except LookupError:
                return {"miss": True}
            except Exception as e:
                # chain/digest corruption: a TYPED refusal the fetching
                # side surfaces as snapshot_torn, never a wedged socket
                return {"refused": "%s: %s" % (type(e).__name__, e)}
        if cmd == "mb_status":
            return {"owners": {str(o): m for o, m in
                               (self.mailbox.meta() or {}).items()},
                    "resident_bytes": self.mailbox.resident_bytes()}
        return {"error": "unknown cmd %r" % cmd}

    def close(self):
        if self._dead:
            return
        self._dead = True
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
