"""Socket-backed pod rendezvous — the network transport under
:class:`~.coordination.SocketCoordinator`.

Reference parity: the reference pod coordinates over the network (the
pserver/brpc RPC tier — trainers and pservers share no filesystem, only
sockets). FileCoordinator ports the *protocol* but not the transport: it
assumes a shared directory, and it only learns a host died when someone
*declares* it. This module supplies the real thing with nothing but the
stdlib:

  * :class:`CoordServer` — one small TCP service holding the
    coordination KV state: gather rounds (with the STICKY completion
    semantics of Local/FileCoordinator: the first completion freezes the
    member snapshot for every participant), tombstones (fencing), join
    announcements, and per-host heartbeats. A background monitor
    tombstones any registered host whose heartbeat goes stale past
    ``hb_deadline_s`` — liveness becomes a property of the transport,
    not of someone calling ``mark_lost``. Runnable in-process for tests
    (``CoordServer(n).start()``) or standalone via ``tools/coordsvc.py``.
  * :class:`CoordClient` — a tiny request/response client. Transient
    socket errors are retried through the shared
    :class:`~.resilience.RetryPolicy` (reconnect, then re-send — every
    server op is idempotent, round contributions keyed by
    ``(name, host_id)`` plus a client token so a replay after a broken
    pipe never double-counts and an imposter never overwrites). A
    daemon heartbeat thread keeps this host live and feeds the
    observability gauges.

Wire protocol: newline-delimited JSON, one request object per line, one
response object per line, connections long-lived. Values are anything
JSON encodes — the same envelope FileCoordinator already writes to its
round files.

Observability (rides ``resilience.metrics()``):
  transport_reconnects_total   counter — client reconnect attempts
  transport_heartbeat_lag      per-host gauge — seconds a host's
                               heartbeat cadence is running behind
                               (0 when healthy; grows during stalls)
"""
import collections
import json
import socket
import socketserver
import threading
import time

from .resilience import RetryPolicy, record_event

__all__ = ["TransportError", "CoordServer", "CoordClient"]

_DEFAULT_HB_INTERVAL_S = 0.5


class TransportError(ConnectionError):
    """The coordination service could not be reached (after retries).
    Subclasses ConnectionError so resilience.classify treats it as
    transient — the caller's RetryPolicy decides when to give up."""


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

class _PodState(object):
    """The coordination KV state, guarded by one lock.

    Mirrors FileCoordinator's directory layout in memory:
      lost:   {host_id: reason}           tombstones (fencing)
      joins:  {host_id: nonce}            fenced hosts asking back in
      rounds: {name: {"values", "tokens", "done", "acks"}}
      hb:     {host_id: last monotonic}   heartbeats (hello/hb)
      info:   {host_id: blob}             member-published JSON blobs
                                          (serving address, generation —
                                          see ``put_info``/``members``)
    ``completed`` keeps the most recent frozen round names (bounded
    deque — a long-running service must not grow by one string per
    round forever) for test and tooling introspection.

    ``n_hosts=None`` starts the service in AUTO-SIZE mode: the pod size
    is learned from the first ``hello`` that carries ``n_hosts`` (every
    SocketCoordinator sends it), and every later hello must agree.
    Until then only ``hello`` is served — any other op would need the
    size for range checks and round completion.
    """

    def __init__(self, n_hosts, hb_deadline_s=None):
        self.n_hosts = None if n_hosts is None else int(n_hosts)
        self.hb_deadline_s = None if hb_deadline_s is None \
            else float(hb_deadline_s)
        self.lock = threading.Lock()
        self.lost = {}
        # bumped on EVERY membership mutation (tombstone and unfence):
        # clients order the lost maps they observe by it, so a stale
        # response processed late can never resurrect a cleared
        # tombstone (or re-fire loss hooks for a readmitted host)
        self.lost_version = 0
        self.joins = {}
        self.rounds = {}
        self.hb = {}
        self.info = {}
        self.completed = collections.deque(maxlen=2048)

    # -- callers hold self.lock ------------------------------------------
    def _mark_lost(self, host_id, reason):
        if host_id in self.lost:
            return False
        self.lost[host_id] = str(reason)
        self.lost_version += 1
        self.joins.pop(host_id, None)
        return True

    def _scan_heartbeats(self, now):
        """Tombstone every registered, un-fenced host whose heartbeat is
        older than the deadline. Returns the newly lost ids."""
        if self.hb_deadline_s is None:
            return []
        newly = []
        for hid, last in list(self.hb.items()):
            if hid in self.lost:
                continue
            age = now - last
            if age > self.hb_deadline_s:
                if self._mark_lost(hid, "missed heartbeat (%.2fs > %.2fs)"
                                   % (age, self.hb_deadline_s)):
                    newly.append(hid)
        return newly

    def _freeze_if_complete(self, name):
        """STICKY completion (Local/FileCoordinator parity): the first
        observation of every live host present freezes the member
        snapshot; later membership changes cannot re-open the round."""
        r = self.rounds.get(name)
        if r is None or r["done"] is not None:
            return
        present = set(r["values"])
        waiting = [i for i in range(self.n_hosts)
                   if i not in self.lost and i not in present]
        if waiting:
            return
        r["done"] = sorted(present - set(self.lost))
        self.completed.append(name)


class CoordServer(object):
    """The rendezvous service: TCP + threads, stdlib only.

    One per pod. Start in-process (tests, or the host-0 sidecar
    pattern) or standalone through ``tools/coordsvc.py``. ``port=0``
    binds an ephemeral port — read it back from :attr:`address`.
    ``n_hosts=None`` starts in auto-size mode: the pod size is learned
    from the first hello that carries one (``tools/coordsvc.py
    --n-hosts auto``) — elastic group sizes without up-front config.

    ``hb_deadline_s`` arms heartbeat liveness: any host that ever said
    hello and then goes silent past the deadline is tombstoned by the
    monitor thread, exactly as if a peer had declared it lost — clients
    observe the tombstone on their next heartbeat/poll and fire their
    loss hooks. ``None`` disables the monitor (losses then come only
    from explicit ``mark_lost`` / gather deadlines, the FileCoordinator
    default)."""

    def __init__(self, n_hosts, port=0, host="127.0.0.1",
                 hb_deadline_s=None):
        self._state = _PodState(n_hosts, hb_deadline_s=hb_deadline_s)
        state = self._state

        class _Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        req = json.loads(line)
                        resp = _serve(state, req)
                    except Exception as e:   # malformed request
                        resp = {"error": "%s: %s" % (type(e).__name__, e)}
                    self.wfile.write(json.dumps(resp).encode() + b"\n")
                    self.wfile.flush()

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self.address = "%s:%d" % self._server.server_address[:2]
        self._threads = []
        self._closed = threading.Event()

    @property
    def state(self):
        """The live :class:`_PodState` — in-process introspection for
        tests and the host-0 sidecar (read under ``state.lock``)."""
        return self._state

    def start(self):
        t = threading.Thread(target=self._server.serve_forever,
                             daemon=True, name="paddle_tpu-coordsvc")
        t.start()
        self._threads.append(t)
        if self._state.hb_deadline_s is not None:
            m = threading.Thread(target=self._monitor, daemon=True,
                                 name="paddle_tpu-coordsvc-hb")
            m.start()
            self._threads.append(m)
        return self

    def _monitor(self):
        period = max(0.01, self._state.hb_deadline_s / 4.0)
        while not self._closed.wait(period):
            with self._state.lock:
                newly = self._state._scan_heartbeats(time.monotonic())
            for hid in newly:
                record_event("hb_lost", host_lost=hid)

    def close(self):
        self._closed.set()
        self._server.shutdown()
        self._server.server_close()
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _serve(state, req):
    """Dispatch one request against the pod state. Every op is
    idempotent so a client may blindly re-send after a reconnect."""
    cmd = req.get("cmd")
    hid = req.get("host")
    hid = None if hid is None else int(hid)
    now = time.monotonic()
    with state.lock:
        # both guards read state.n_hosts INSIDE the lock: in auto-size
        # mode a non-hello op racing the first sized hello must see
        # one consistent value — a torn read could skip the range
        # check and land exactly the phantom state it exists to block
        if hid is not None and state.n_hosts is not None \
                and not 0 <= hid < state.n_hosts:
            # an off-by-one host id must fail loudly, not land phantom
            # contributions in rounds or phantom tombstones
            return {"error": "host id %d out of range for a %d-host "
                    "pod" % (hid, state.n_hosts)}
        if state.n_hosts is None and cmd != "hello":
            # auto-size mode before the first sized hello: nothing
            # else can be range-checked or frozen yet
            return {"error": "pod size not learned yet — the first "
                    "hello must carry n_hosts (auto-size mode)"}
        # the heartbeat monitor owns proactive scans, but piggybacking
        # one on every request keeps detection sharp under load (and
        # makes the deadline hold even on a paused monitor thread)
        state._scan_heartbeats(now)
        resp = _dispatch(state, cmd, hid, req, now)
        if "lost" in resp:
            # every lost map ships with its version: the client drops
            # any map older than one it already applied, so a response
            # processed late cannot resurrect a cleared tombstone
            resp["lost_v"] = state.lost_version
        return resp


def _dispatch(state, cmd, hid, req, now):
    """The op table — caller holds ``state.lock``."""
    if cmd == "hello":
        if state.n_hosts is None:
            # auto-size: the first sized hello fixes the pod size for
            # the service's lifetime; later hellos must agree. The
            # validation runs BEFORE the commit — an error return must
            # not have the side effect of pinning a bogus size
            if req.get("n_hosts") is None:
                return {"error": "pod size not learned yet — this "
                        "hello must carry n_hosts (auto-size mode)"}
            want = int(req["n_hosts"])
            if want < 1:
                return {"error": "n_hosts must be >= 1, got %d" % want}
            if hid is not None and not 0 <= hid < want:
                return {"error": "host id %d out of range for a "
                        "%d-host pod" % (hid, want)}
            state.n_hosts = want
        if int(req.get("n_hosts", state.n_hosts)) != state.n_hosts:
            return {"error": "pod size mismatch: server has %d "
                    "hosts, client expects %s"
                    % (state.n_hosts, req.get("n_hosts"))}
        if hid is not None and req.get("lease"):
            # only heartbeating clients take a liveness lease: a
            # passive observer (heartbeat=False) that registered
            # one would be tombstoned the moment it went stale
            state.hb[hid] = now
        return {"ok": True, "n_hosts": state.n_hosts,
                "lost": dict(state.lost)}
    if cmd == "hb":
        if hid is not None:
            state.hb[hid] = now
        return {"ok": True, "lost": dict(state.lost)}
    if cmd == "lost":
        return {"lost": dict(state.lost)}
    if cmd == "mark_lost":
        state._mark_lost(hid, req.get("reason", "declared lost"))
        return {"ok": True, "lost": dict(state.lost)}
    if cmd == "announce_join":
        if hid not in state.lost:
            return {"error": "host %d is not fenced — only a lost "
                    "host announces a rejoin" % hid}
        state.joins[hid] = int(req.get("nonce", 0))
        return {"ok": True}
    if cmd == "pending_joins":
        return {"joins": dict(state.joins)}
    if cmd == "unfence":
        if state.lost.pop(hid, None) is not None:
            state.lost_version += 1
        state.joins.pop(hid, None)
        # the un-fenced host re-enters liveness with a fresh lease —
        # without this its pre-fence stale heartbeat would re-fence
        # it on the very next monitor scan
        if hid in state.hb:
            state.hb[hid] = now
        # the response CARRIES the post-unfence lost map: the caller's
        # client applies its (bumped) version before the coordinator
        # forgets the host, so any straggling pre-unfence callback is
        # dropped by the version guard instead of resurrecting the loss
        return {"ok": True, "lost": dict(state.lost)}
    if cmd == "put":
        name = req["name"]
        if hid in state.lost:
            return {"fenced": state.lost[hid], "lost": dict(state.lost)}
        r = state.rounds.setdefault(
            name, {"values": {}, "tokens": {}, "done": None,
                   "acks": set()})
        token = req.get("token")
        if hid in r["values"]:
            if r["tokens"].get(hid) == token and token is not None:
                # the same client re-sending after a reconnect:
                # idempotent, keyed by (name, host_id, token)
                return {"ok": True, "resent": True}
            return {"error": "host %d already contributed to round "
                    "%r — collective names must be unique per round"
                    % (hid, name)}
        if r["done"] is not None:
            # frozen without us: we were fenced when the snapshot
            # was taken — arriving now must not mutate it
            return {"fenced": state.lost.get(
                hid, "round %r froze without host %d" % (name, hid)),
                "lost": dict(state.lost)}
        r["values"][hid] = req.get("value")
        r["tokens"][hid] = token
        state._freeze_if_complete(name)
        return {"ok": True}
    if cmd == "poll":
        name = req["name"]
        r = state.rounds.get(name)
        if hid in state.lost and (r is None or r["done"] is None
                                  or hid not in r["done"]):
            return {"fenced": state.lost[hid], "lost": dict(state.lost)}
        if r is None:
            return {"error": "round %r unknown — poll follows put"
                    % name}
        state._freeze_if_complete(name)
        if r["done"] is None:
            waiting = [i for i in range(state.n_hosts)
                       if i not in state.lost
                       and i not in r["values"]]
            return {"waiting": waiting, "lost": dict(state.lost)}
        return {"done": r["done"],
                "values": {str(i): r["values"][i] for i in r["done"]},
                "lost": dict(state.lost)}
    if cmd == "ack":
        name = req["name"]
        r = state.rounds.get(name)
        if r is not None and r["done"] is not None:
            r["acks"].add(hid)
            if r["acks"] >= set(r["done"]):
                # last one out cleans up (File/LocalCoordinator
                # parity) — the rounds table stays bounded
                state.rounds.pop(name, None)
        return {"ok": True}
    if cmd == "put_info":
        # member-published blob (last write wins, idempotent): how a
        # serving replica advertises its HTTP address + generation so
        # the router never needs static fleet configuration
        if hid is None:
            return {"error": "put_info needs a host id"}
        state.info[hid] = req.get("info")
        return {"ok": True}
    if cmd == "members":
        # one poll answers the whole routing question: who is
        # registered (info), who is fenced (lost — versioned by the
        # caller in _serve), and how stale each liveness lease is.
        # The server's deadline ships too, so clients can judge a
        # lease "live-looking" by the SAME bound the monitor fences by
        return {"n_hosts": state.n_hosts,
                "hb_deadline_s": state.hb_deadline_s,
                "hb_age": {str(h): round(now - t, 6)
                           for h, t in state.hb.items()},
                "info": {str(h): v for h, v in state.info.items()},
                "lost": dict(state.lost)}
    return {"error": "unknown cmd %r" % cmd}


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class CoordClient(object):
    """Request/response client with transparent reconnect.

    One per (process, host_id). All requests serialize on one socket
    under a lock — the heartbeat thread shares it, so ordering is
    strict and the server never sees interleaved lines. A send/recv
    failure tears the socket down and retries through ``retry_policy``
    (connect + re-send; server ops are idempotent), recording a
    ``transport_reconnect`` event per re-dial so
    ``transport_reconnects_total`` counts real network pain.

    ``hb_interval_s`` starts the daemon heartbeat on :meth:`start_heartbeat`
    callers; each beat refreshes this host's liveness lease and records
    the ``transport_hb_lag`` gauge — seconds the cadence is running
    late (0 when healthy). The latest ``lost`` map from any response is
    kept on :attr:`last_lost` for the owner to diff against."""

    def __init__(self, address, host_id=None, retry_policy=None,
                 connect_timeout_s=5.0, io_timeout_s=30.0):
        if isinstance(address, (tuple, list)):
            self._addr = (address[0], int(address[1]))
        else:
            host, _, port = address.rpartition(":")
            self._addr = (host or "127.0.0.1", int(port))
        self.host_id = None if host_id is None else int(host_id)
        # the default budget rides out a SUPERVISED RESTART of the
        # rendezvous service (~5-10s of backoff), not just a dropped
        # connection — the documented "coordinator death is a transient
        # outage" promise holds only as long as this budget; pass a
        # bigger retry_policy for slower orchestrators
        self._policy = retry_policy or RetryPolicy(
            max_attempts=9, base_delay_s=0.1, max_delay_s=2.0)
        self._connect_timeout_s = float(connect_timeout_s)
        # every server op answers immediately (no server-side blocking),
        # so a bounded read is purely a hang guard: a wedged service
        # must not pin the request lock — and with it the heartbeat AND
        # gather threads — forever
        self._io_timeout_s = None if io_timeout_s is None \
            else float(io_timeout_s)
        self._lock = threading.Lock()
        self._sock = None
        self._rfile = None
        self._closed = False
        self._hb_thread = None
        self._hb_stop = threading.Event()
        self.last_lost = {}
        self._lost_cb = None
        # ordering guard for the lost map: responses finish their
        # roundtrip under _lock but are PROCESSED after releasing it,
        # so a slow thread could apply a stale map after a newer one
        # (resurrecting a cleared tombstone). The server versions every
        # map; we only ever apply forward.
        self._lost_lock = threading.Lock()
        self._lost_v = -1
        # instantaneous heartbeat-cadence lag, updated every beat (the
        # recorded gauge EVENTS are throttled — see _hb_loop)
        self.hb_lag_s = 0.0

    # -- wire --------------------------------------------------------------
    def _connect_locked(self):
        sock = socket.create_connection(
            self._addr, timeout=self._connect_timeout_s)
        sock.settimeout(self._io_timeout_s)
        self._sock = sock
        self._rfile = sock.makefile("rb")

    def _teardown_locked(self):
        for closer in (self._rfile, self._sock):
            try:
                if closer is not None:
                    closer.close()
            except OSError:
                pass
        self._sock = self._rfile = None

    def _roundtrip_locked(self, payload):
        if self._sock is None:
            self._connect_locked()
        self._sock.sendall(payload)
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("coordination service closed the "
                                  "connection")
        return json.loads(line)

    def request(self, req):
        """One request/response round trip; reconnects and re-sends on
        transient socket failure (requests are idempotent server-side).
        Raises :class:`TransportError` once the retry budget is spent."""
        payload = json.dumps(req).encode() + b"\n"
        last = None
        for attempt in range(self._policy.max_attempts):
            with self._lock:
                if self._closed:
                    raise TransportError("client is closed")
                try:
                    return self._roundtrip_locked(payload)
                except (OSError, ValueError) as e:
                    # ValueError: a torn JSON line from a half-closed
                    # socket — same remedy as any socket error
                    last = e
                    self._teardown_locked()
            if attempt + 1 >= self._policy.max_attempts:
                break
            delay = self._policy.delay_s(attempt)
            record_event("transport_reconnect", attempt=attempt + 1,
                         error=type(last).__name__, backoff_s=delay,
                         host=self.host_id)
            self._policy.sleep(delay)
        raise TransportError(
            "coordination service at %s:%d unreachable after %d "
            "attempts; last error: %r"
            % (self._addr[0], self._addr[1], self._policy.max_attempts,
               last))

    def call(self, cmd, **fields):
        """request() + server-error unwrapping. Returns the response
        dict; a server-side ``error`` raises RuntimeError (the caller
        maps it onto the Coordinator error taxonomy). Tracks the most
        recent ``lost`` map for the owner's loss observation."""
        req = dict(fields, cmd=cmd)
        if self.host_id is not None and "host" not in req:
            req["host"] = self.host_id
        resp = self.request(req)
        if "lost" in resp:
            parsed = {int(k): v for k, v in resp["lost"].items()}
            version = int(resp.get("lost_v", 0))
            with self._lost_lock:
                if version >= self._lost_v:
                    self._lost_v = version
                    self.last_lost = parsed
                # the callback always sees the NEWEST map known to this
                # client (never a stale response's own) AND its version
                # — the consumer re-checks it under ITS lock, because
                # this invocation happens outside ours and a delayed
                # thread could otherwise deliver a pre-unfence map
                # after the owner already readmitted the host
                current = dict(self.last_lost)
                current_v = self._lost_v
            cb = self._lost_cb
            if cb is not None:
                cb(current, current_v)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp

    # -- heartbeat ---------------------------------------------------------
    def start_heartbeat(self, interval_s=_DEFAULT_HB_INTERVAL_S,
                        on_lost=None):
        """Say hello (registers this host's liveness lease) and start
        the daemon heartbeat. ``on_lost(lost_map)`` fires on every
        response that carries a lost map — the SocketCoordinator hangs
        its loss observation here so tombstones written by the server's
        deadline monitor reach the survivors' hooks without any gather
        in flight."""
        self._lost_cb = on_lost
        self._hb_interval_s = float(interval_s)
        self.call("hello", lease=True)
        t = threading.Thread(target=self._hb_loop, daemon=True,
                             name="paddle_tpu-hb-%s" % self.host_id)
        self._hb_thread = t
        t.start()
        return self

    def _hb_loop(self):
        last_beat = time.monotonic()
        last_recorded = 0.0
        beats = 0
        while not self._hb_stop.wait(self._hb_interval_s):
            try:
                self.call("hb")
            except (TransportError, RuntimeError):
                # the reconnect events already counted the pain; the
                # lease simply ages until the server or network heals
                continue
            now = time.monotonic()
            lag = max(0.0, (now - last_beat) - self._hb_interval_s)
            last_beat = now
            self.hb_lag_s = lag
            beats += 1
            # the gauge event is THROTTLED: the event log is a bounded
            # deque shared with the recovery history, and an unthrotted
            # 2 Hz stream would evict everything else within the hour.
            # Record when the cadence actually slipped (the signal) or
            # every ~60s as a keepalive so the gauge stays fresh; the
            # instantaneous value is always on .hb_lag_s.
            keepalive = max(1, int(60.0 / max(self._hb_interval_s,
                                              1e-3)))
            if lag > self._hb_interval_s or lag > last_recorded * 2 \
                    or beats % keepalive == 0:
                last_recorded = lag
                record_event("transport_hb_lag", host=self.host_id,
                             lag_s=lag)

    def close(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        with self._lock:
            self._closed = True
            self._teardown_locked()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
