"""Resilience subsystem — fault injection, retry/backoff, auto-recovery.

Reference parity: the reference stack survives real fleets through three
mechanisms — collective ops carry timeouts (operators/collective/),
the transpiler emits ``checkpoint_notify`` so trainers snapshot around
faults, and pserver trainers reconnect after transient RPC failures.
This module is the TPU-native port of that recovery story, closing the
detect -> recover loop that watchdog.py (detect a hung step) and
io.save_checkpoint (crash-consistent snapshots) leave open:

  * :class:`FaultInjector` — a deterministic, seeded chaos harness with
    named injection points (``step``, ``ckpt_write``, ``serve``) so every
    recovery path is exercised by fast CPU-backend tests, not hope.
  * :class:`RetryPolicy` — exponential backoff with jitter plus a
    transient/fatal classifier (CollectiveTimeoutError and injected
    preemptions are retryable; shape/sharding errors are not).
  * :class:`ResilientTrainer` — drives Executor.run / run_steps; on a
    retryable step failure it restores the latest VALID checkpoint,
    rewinds the step counter and resumes, under a bounded restart
    budget.
  * :func:`run_with_deadline` — per-request deadline used by
    ServingPredictor for graceful degradation (load shedding +
    warm-bucket fallback live in serving.py).
  * a structured event log (:func:`events`) recording every fault,
    retry, restore, shed and degradation for observability.

Env knobs (read once; ``reload_env()`` re-reads):
  PADDLE_TPU_FAULTS       fault spec string, e.g.
                          ``step:preempt@5;serve:slow=2.0@3``
  PADDLE_TPU_FAULT_SEED   seed for probabilistic (``~p``) specs
"""
import collections
import contextlib
import logging
import os
import random
import threading
import time

from . import watchdog
from .watchdog import CollectiveTimeoutError, bounded_call

__all__ = [
    "FaultSpec", "FaultInjector", "RetryPolicy", "ResilientTrainer",
    "SimulatedPreemptionError", "SimulatedHostDeathError",
    "ServerOverloadedError",
    "DeadlineExceededError", "RestartBudgetExceededError",
    "NumericFaultError", "SkipBudgetExceededError", "SDCDetector",
    "fire", "inject", "install", "current_injector", "reload_env",
    "events", "record_event", "clear_events", "classify",
    "run_with_deadline", "INJECTION_POINTS", "context",
    "metrics", "metrics_text", "parse_metrics_text",
    "serve_metrics", "MetricsServer", "ElasticTrainer",
    "record_bytes", "bytes_totals", "clear_bytes",
    "record_buddy_gen", "buddy_gens", "clear_buddy_gens",
    "record_buddy_resident", "buddy_resident",
    "record_buddy_delta_ratio", "buddy_delta_ratio",
    "record_buddy_fetch_ms", "buddy_fetch_ms",
    "record_router_request", "record_router_retry",
    "observe_router_batch",
    "set_router_queue_depth", "set_router_inflight",
    "record_router_slow",
    "router_totals", "clear_router",
    "observe_executor_step", "executor_step_totals", "clear_exec",
    "record_analysis", "analysis_totals", "clear_analysis",
]

INJECTION_POINTS = ("step", "ckpt_write", "serve")


def _logger():
    from ..log_helper import get_logger
    return get_logger("paddle_tpu.resilience", logging.WARNING,
                      fmt="%(asctime)s-%(levelname)s: %(message)s")


# ---------------------------------------------------------------------------
# errors
# ---------------------------------------------------------------------------

class SimulatedPreemptionError(RuntimeError):
    """Injected stand-in for a preempted/evicted host: the step dies the
    way a real preemption surfaces (an exception out of the dispatch),
    and recovery must restore + replay."""


class SimulatedHostDeathError(RuntimeError):
    """Injected stand-in for a host LEAVING the pod (eviction notice,
    node reclaim): unlike a transient preemption the process is going
    away, so the local trainer cannot retry. Only
    coordination.ElasticTrainer handles the raised error (fence self,
    survivors continue elastically); everywhere else it classifies
    FATAL — a plain (Pod)ResilientTrainer cannot outlive its own host.
    A real ABRUPT death needs no exception at all: the survivors'
    gather timeout fences the silent host and the pod rewinds without
    it."""


class ServerOverloadedError(RuntimeError):
    """Load shedding: the serving in-flight cap is full. Clients should
    back off and retry — the deliberate alternative to queue collapse."""


class DeadlineExceededError(CollectiveTimeoutError):
    """A per-request serving deadline expired. Subclasses
    CollectiveTimeoutError so existing timeout handling (and the
    transient classifier) treat it uniformly."""


class RestartBudgetExceededError(RuntimeError):
    """ResilientTrainer exhausted its restart budget — the fault is not
    transient at this rate; escalate to the orchestrator."""


class NumericFaultError(FloatingPointError):
    """A step produced a non-finite value and the numeric policy wants
    a recovery, not a plain raise.  Subclasses FloatingPointError so
    every existing handler (and the transient classifier) treats it
    like today's check_numerics raise; additionally carries WHERE the
    fault was localized so recovery can name the culprit and skip the
    poison batch on replay.

    ``step``    executor step counter at the faulting step
    ``culprit`` first offending var name (fetch/param/grad), or None
    ``batch_index`` global batch index of the poison batch (filled in
                by the trainer's feed loop; None when not feed-driven)
    """

    def __init__(self, msg, step=None, culprit=None, batch_index=None,
                 window_offset=0):
        super(NumericFaultError, self).__init__(msg)
        self.step = step
        self.culprit = culprit
        self.batch_index = batch_index
        # which batch INSIDE the faulting dispatch window blew up
        # (run_steps localizes it post-hoc); the trainer adds its own
        # window base to get the global batch_index
        self.window_offset = window_offset


class SkipBudgetExceededError(NumericFaultError):
    """numeric_policy="skip" discarded more consecutive steps than the
    configured budget allows — the fault is persistent, not a one-batch
    poison; escalate instead of silently dropping the whole stream."""


# ---------------------------------------------------------------------------
# structured event log
# ---------------------------------------------------------------------------

_tls = threading.local()


@contextlib.contextmanager
def context(**tags):
    """Attach tags to every event THIS thread records inside the block.

    PodResilientTrainer wraps each simulated host's loop in
    ``context(host=i)`` so one process-global event log still tells the
    hosts apart — the same shape a real pod gets from per-process logs."""
    old = getattr(_tls, "tags", None)
    merged = dict(old or {})
    merged.update(tags)
    _tls.tags = merged
    try:
        yield
    finally:
        _tls.tags = old


class EventLog(object):
    """Bounded, thread-safe, append-only record of resilience activity.

    Each event is a plain dict with at least ``kind`` and ``time`` —
    cheap to export to any metrics pipe later."""

    def __init__(self, capacity=4096):
        self._events = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def record(self, kind, **fields):
        tags = getattr(_tls, "tags", None)
        event = dict(tags) if tags else {}
        event.update(fields)
        event["kind"] = kind
        event["time"] = time.time()
        with self._lock:
            self._events.append(event)
        return event

    def events(self, kind=None):
        with self._lock:
            evs = list(self._events)
        if kind is None:
            return evs
        return [e for e in evs if e["kind"] == kind]

    def clear(self):
        with self._lock:
            self._events.clear()


_LOG = EventLog()


def events(kind=None):
    """All recorded resilience events (optionally filtered by kind)."""
    return _LOG.events(kind)


def record_event(kind, **fields):
    return _LOG.record(kind, **fields)


def clear_events():
    """Reset the observability surface: the bounded event log AND the
    cumulative byte/router counters (a cleared log exporting stale
    series would break the 'empty log -> empty metrics' contract tests
    and scrapers rely on)."""
    _LOG.clear()
    clear_bytes()
    clear_router()
    clear_exec()
    clear_kernel_choice()
    clear_analysis()
    clear_buddy_gens()


# ---------------------------------------------------------------------------
# metrics export (Prometheus-style aggregation of the event log)
# ---------------------------------------------------------------------------

METRIC_PREFIX = "paddle_tpu_resilience"
# restore latencies span "local disk, small model" (~ms) to "multi-host
# resharded restore" (~minutes)
RESTORE_LATENCY_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0)

# Wire-byte accounting of the compressed movement paths (quantized
# collectives / elastic state ship / checkpoint payloads). Cumulative
# process-global counters OUTSIDE the bounded event log: per-step
# increments at dispatch rate would evict the whole log within minutes,
# and counters must never wrap anyway. Channel -> {"raw", "wire"}.
_BYTES = {}
_BYTES_LOCK = threading.Lock()
BYTES_CHANNELS = ("collective", "stateship", "ckpt", "buddy_snapshot")


def record_bytes(channel, raw, wire):
    """Accumulate one transfer's byte accounting: ``raw`` is what the
    uncompressed path would have moved, ``wire`` what actually crossed
    the wire/disk. Exported by :func:`metrics` as the counter pair
    ``<prefix>_<channel>_bytes_total{kind="raw"|"wire"}``."""
    with _BYTES_LOCK:
        c = _BYTES.setdefault(str(channel), {"raw": 0, "wire": 0})
        c["raw"] += int(raw)
        c["wire"] += int(wire)


# Buddy-snapshot generation gauges: one value per host at WINDOW rate —
# a per-window event would churn the bounded log, so the last published
# generation lives in a cumulative store (cleared with the log). The
# serving probe's strict mode compares these across live hosts: a
# divergence of more than one window means some host's snapshots are
# not landing.
_BUDDY_GEN = {}
_BUDDY_GEN_LOCK = threading.Lock()


def record_buddy_gen(host, gen):
    """Record the buddy-snapshot generation ``host`` last published
    (or adopted at restore). Exported by :func:`metrics` as the gauge
    ``<prefix>_buddy_generation{host=}``."""
    with _BUDDY_GEN_LOCK:
        _BUDDY_GEN[int(host)] = int(gen)


def buddy_gens():
    """{host: generation} snapshot of the buddy-generation gauges."""
    with _BUDDY_GEN_LOCK:
        return dict(_BUDDY_GEN)


def clear_buddy_gens():
    with _BUDDY_GEN_LOCK:
        _BUDDY_GEN.clear()
    with _BUDDY_P2P_LOCK:
        _BUDDY_RESIDENT.clear()
        _BUDDY_P2P.clear()


# P2p buddy-mailbox gauges (window/restore rate, so cumulative stores
# outside the event log, cleared with the generation gauges).
# _BUDDY_RESIDENT keys are STRINGS: mailbox hosts record under their
# host id, the coordinator records its legacy-blob + metadata residency
# under "coord" — the strict probe's memory-ceiling gate reads that row
# and fails if the coordinator is holding payloads again.
_BUDDY_RESIDENT = {}
_BUDDY_P2P = {}
_BUDDY_P2P_LOCK = threading.Lock()


def record_buddy_resident(host, nbytes):
    """Record the bytes resident in ``host``'s buddy mailbox (or, for
    host="coord", in the coordinator's buddy stores). Exported by
    :func:`metrics` as ``<prefix>_buddy_resident_bytes{host=}``."""
    with _BUDDY_P2P_LOCK:
        _BUDDY_RESIDENT[str(host)] = int(nbytes)


def buddy_resident():
    """{host: bytes} snapshot of the mailbox-residency gauges."""
    with _BUDDY_P2P_LOCK:
        return dict(_BUDDY_RESIDENT)


def record_buddy_delta_ratio(ratio):
    """Record one boundary send's wire ratio (this send's wire bytes /
    the last FULL send's wire bytes — 1.0 for a full send, < 1 when the
    delta skip is earning its keep). Exported as the gauge
    ``<prefix>_buddy_delta_ratio``."""
    with _BUDDY_P2P_LOCK:
        _BUDDY_P2P["delta_ratio"] = float(ratio)


def buddy_delta_ratio():
    with _BUDDY_P2P_LOCK:
        return _BUDDY_P2P.get("delta_ratio")


def record_buddy_fetch_ms(ms):
    """Record one host-to-host mailbox pull's latency. Exported as the
    gauge ``<prefix>_buddy_p2p_fetch_ms``."""
    with _BUDDY_P2P_LOCK:
        _BUDDY_P2P["fetch_ms"] = float(ms)


def buddy_fetch_ms():
    with _BUDDY_P2P_LOCK:
        return _BUDDY_P2P.get("fetch_ms")


# Trace-time kernel-selection accounting (ops.pallas_dispatch.choose):
# one increment per call-site decision at COMPILE rate, so cumulative
# process counters (not events) keyed (op, impl, source) — "is the
# fleet actually running the tuned/predicted kernels it thinks it is"
# becomes a scrapeable series instead of a log grep.
_KCHOICE = {}
_KCHOICE_LOCK = threading.Lock()


def record_kernel_choice(op, impl, source):
    """Count one trace-time kernel decision (see pallas_dispatch.
    KernelChoice): exported by :func:`metrics` as
    ``<prefix>_kernel_choice_total{op=,impl=,source=}``."""
    with _KCHOICE_LOCK:
        k = (str(op), str(impl), str(source))
        _KCHOICE[k] = _KCHOICE.get(k, 0) + 1


def kernel_choice_totals():
    """Snapshot ``{(op, impl, source): count}``."""
    with _KCHOICE_LOCK:
        return dict(_KCHOICE)


def clear_kernel_choice():
    with _KCHOICE_LOCK:
        _KCHOICE.clear()


# Program-verifier accounting (framework/analysis.py): one increment per
# diagnostic at COMPILE rate, so cumulative process counters keyed
# (pass, severity) — "is the fleet compiling clean programs" becomes a
# scrapeable series; the per-verification summary rides the event log
# as `program_analysis` events (analysis.report).
_ANALYSIS = {}
_ANALYSIS_LOCK = threading.Lock()


def record_analysis(pass_name, severity, n=1):
    """Count verifier diagnostics: exported by :func:`metrics` as
    ``<prefix>_analysis_diagnostics_total{pass=,severity=}``."""
    with _ANALYSIS_LOCK:
        k = (str(pass_name), str(severity))
        _ANALYSIS[k] = _ANALYSIS.get(k, 0) + int(n)


def analysis_totals():
    """Snapshot ``{(pass, severity): count}``."""
    with _ANALYSIS_LOCK:
        return dict(_ANALYSIS)


def clear_analysis():
    with _ANALYSIS_LOCK:
        _ANALYSIS.clear()


def bytes_totals():
    """Snapshot of the cumulative byte counters:
    ``{channel: {"raw": n, "wire": n}}``."""
    with _BYTES_LOCK:
        return {ch: dict(c) for ch, c in _BYTES.items()}


def clear_bytes():
    with _BYTES_LOCK:
        _BYTES.clear()


# Executor step-phase latency (the obs tentpole's always-on metrics
# half): per-phase cumulative histograms OUTSIDE the event log — steps
# run at dispatch rate. Kind is the phase ("compile", "execute",
# "writeback", "total"); buckets span a CPU toy step (~ms) to a cold
# multi-minute XLA compile.
EXEC_STEP_BUCKETS = (0.0005, 0.002, 0.01, 0.05, 0.25, 1.0, 5.0, 30.0,
                     120.0)
_EXEC = {}
_EXEC_LOCK = threading.Lock()


def observe_executor_step(kind, seconds):
    """Record one executor step phase's wall time in the
    ``<prefix>_executor_step_seconds{kind=}`` histogram."""
    seconds = float(seconds)
    with _EXEC_LOCK:
        h = _EXEC.setdefault(
            str(kind), {"counts": [0] * (len(EXEC_STEP_BUCKETS) + 1),
                        "sum": 0.0, "count": 0})
        for i, le in enumerate(EXEC_STEP_BUCKETS):
            if seconds <= le:
                h["counts"][i] += 1
                break
        else:
            h["counts"][-1] += 1
        h["sum"] += seconds
        h["count"] += 1


def executor_step_totals():
    """{kind: {"counts", "sum", "count"}} snapshot."""
    with _EXEC_LOCK:
        return {k: {"counts": list(h["counts"]), "sum": h["sum"],
                    "count": h["count"]} for k, h in _EXEC.items()}


def clear_exec():
    with _EXEC_LOCK:
        _EXEC.clear()


# Serving-fleet router accounting (serving_fleet.FleetRouter). Same
# design pressure as the byte counters: the router serves at request
# rate, and one event per request would evict the whole bounded log in
# minutes — so these are cumulative process-global counters/gauges
# OUTSIDE the event log, folded into metrics() only once any activity
# exists (router-less jobs export nothing new). Rare router events
# (a replica dispatch failing over, a rolling-deploy step) still ride
# the ordinary event log.
#
# Every series carries an optional ``router=`` label: N concurrent
# FleetRouters (the HA router tier) share this process-global state,
# and an unlabeled gauge would be overwritten by whichever router
# wrote last — per-router label keys keep the series apart. ``router=
# None`` keeps the historical unlabeled series (single-router callers
# and direct test use are unchanged).
_ROUTER_LOCK = threading.Lock()
ROUTER_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128)


ROUTER_SLOW_K = 8


def _fresh_router_state():
    return {"requests": {},      # (router, outcome) -> count
            "batch": {},         # router -> {"counts", "sum", "count"}
            "queue_depth": {},   # router -> gauge
            "inflight": {},      # (router, replica) -> gauge
            "retries": {},       # (router, replica) -> count
            "slow": {},          # router -> top-K
                                 #   [(latency_s, trace, tenant)]
            # multi-tenant QoS series (additive: the aggregate series
            # above are written unconditionally, so a tenant-less
            # deployment's exposition is bit-for-bit the old one)
            "tenant_requests": {},  # (router, tenant, outcome) -> n
            "expired": {},          # (router, tenant, where) -> n
            "tenant_queue": {}}     # (router, tenant) -> gauge


_ROUTER = _fresh_router_state()


def _router_key(router):
    return None if router is None else str(router)


def record_router_request(outcome, router=None, tenant=None):
    """Count one routed request's terminal outcome ("ok", "shed",
    "deadline", "error", "replay", ...). Exported as
    ``<prefix>_router_requests_total{outcome=[,router=]}``. When the
    caller knows the tenant a SECOND, ``tenant=``-labelled series is
    bumped alongside (never instead of) the aggregate — per-class SLO
    accounting without perturbing the historical series, and the probe
    cross-checks the two for quota-accounting drift."""
    with _ROUTER_LOCK:
        key = (_router_key(router), str(outcome))
        r = _ROUTER["requests"]
        r[key] = r.get(key, 0) + 1
        if tenant is not None:
            tkey = (_router_key(router), str(tenant), str(outcome))
            t = _ROUTER["tenant_requests"]
            t[tkey] = t.get(tkey, 0) + 1


def record_router_retry(replica, router=None):
    """Count one failed dispatch attempt that was retried on a
    sibling. A cumulative counter, NOT an event: under a shed storm
    retries run at request rate and would evict the bounded event log
    (the router still records an event for the RARE connection-level
    failures — a replica death — just not for load-driven 5xx)."""
    with _ROUTER_LOCK:
        key = (_router_key(router), int(replica))
        r = _ROUTER["retries"]
        r[key] = r.get(key, 0) + 1


def observe_router_batch(size, router=None):
    """Record one dispatched micro-batch's coalesced request count in
    the ``<prefix>_router_batch_size`` histogram (per-router series)."""
    size = float(size)
    with _ROUTER_LOCK:
        b = _ROUTER["batch"].setdefault(
            _router_key(router),
            {"counts": [0] * (len(ROUTER_BATCH_BUCKETS) + 1),
             "sum": 0.0, "count": 0})
        for i, le in enumerate(ROUTER_BATCH_BUCKETS):
            if size <= le:
                b["counts"][i] += 1
                break
        else:
            b["counts"][-1] += 1
        b["sum"] += size
        b["count"] += 1


def record_router_slow(latency_s, trace=None, router=None,
                       tenant=None):
    """Keep this request as a slow-request EXEMPLAR if it makes the
    router's top-K by latency. Exemplars pair the p99 a histogram can
    only bound with the trace id that lets an operator pull the exact
    offending timeline (``tools/traceview.py``) — the classic
    metrics-to-trace bridge — and the tenant, so "whose request was
    slow" is one lookup. Exported by :func:`router_totals` as
    ``slow_requests``."""
    latency_s = float(latency_s)
    with _ROUTER_LOCK:
        top = _ROUTER["slow"].setdefault(_router_key(router), [])
        top.append((latency_s, None if trace is None else str(trace),
                    None if tenant is None else str(tenant)))
        top.sort(key=lambda e: -e[0])
        del top[ROUTER_SLOW_K:]


def record_router_expired(where, tenant=None, router=None):
    """Count one request whose propagated deadline budget had already
    expired, by WHERE the expiry was caught:

      * ``"queue"``    expired while waiting in (or arriving at) the
                       router queue — failed 504 WITHOUT dispatching;
      * ``"dispatch"`` expired between batch cut and dispatch — the
                       member is failed alone and the batch recomposed;
      * ``"replica"``  the replica-side guard refused dispatched work
                       that was already expired on arrival. The router
                       checks remaining budget immediately before every
                       send, so this series staying at ZERO is the
                       counter-assertable form of "no request is ever
                       dispatched after its budget expired".

    Exported as ``<prefix>_router_deadline_expired_total{where=,
    tenant=[,router=]}``."""
    with _ROUTER_LOCK:
        key = (_router_key(router),
               "default" if tenant is None else str(tenant),
               str(where))
        e = _ROUTER["expired"]
        e[key] = e.get(key, 0) + 1


def set_router_queue_depth(depth, router=None):
    """Update the ``<prefix>_router_queue_depth`` gauge (requests
    waiting to be coalesced into a batch) for ``router``'s series."""
    with _ROUTER_LOCK:
        _ROUTER["queue_depth"][_router_key(router)] = float(depth)


def set_router_inflight(replica, n, router=None):
    """Update the per-replica ``<prefix>_router_replica_inflight``
    gauge (batches the router currently has dispatched to it)."""
    with _ROUTER_LOCK:
        _ROUTER["inflight"][(_router_key(router), int(replica))] = \
            float(n)


def set_router_tenant_queue_depth(tenant, depth, router=None):
    """Update the per-tenant ``<prefix>_router_tenant_queue_depth``
    gauge (requests waiting in that tenant's WFQ queue). Written only
    by QoS-mode routers, so tenant-less deployments export nothing
    new."""
    with _ROUTER_LOCK:
        _ROUTER["tenant_queue"][(_router_key(router), str(tenant))] = \
            float(depth)


def router_totals(by_router=False):
    """One consistent snapshot of the router accounting. The default
    AGGREGATES across router labels (the historical single-router
    shape): ``{"requests": {outcome: n}, "batch_counts" (per-bucket,
    non-cumulative), "batch_count", "batch_sum", "queue_depth",
    "inflight": {replica: n}, "retries": {replica: n}}``.
    ``by_router=True`` returns the same shape PER ROUTER KEY (None =
    the unlabeled series) — what :func:`metrics` exports from, and
    what the Autoscaler reads its own shed rate out of. Taken under
    ONE lock acquisition so the histogram's bucket counts can never
    run ahead of its total (a non-monotonic histogram is invalid to
    Prometheus consumers). ``slow_requests`` carries the top-K
    slow-request exemplars as ``[{"latency_s", "trace", "tenant"}]``,
    worst first (see :func:`record_router_slow`). QoS additions ride
    as ``"tenants"`` ({tenant: {outcome: n}}), ``"expired"``
    ({where: {tenant: n}}) and ``"tenant_queue_depth"``
    ({tenant: depth}) — all empty for tenant-less deployments."""
    with _ROUTER_LOCK:
        requests = dict(_ROUTER["requests"])
        batch = {r: {"counts": list(b["counts"]), "sum": b["sum"],
                     "count": b["count"]}
                 for r, b in _ROUTER["batch"].items()}
        queue_depth = dict(_ROUTER["queue_depth"])
        inflight = dict(_ROUTER["inflight"])
        retries = dict(_ROUTER["retries"])
        slow = {r: list(v) for r, v in _ROUTER["slow"].items()}
        tenant_requests = dict(_ROUTER["tenant_requests"])
        expired = dict(_ROUTER["expired"])
        tenant_queue = dict(_ROUTER["tenant_queue"])
    routers = (set(r for r, _ in requests) | set(batch)
               | set(queue_depth) | set(r for r, _ in inflight)
               | set(r for r, _ in retries) | set(slow)
               | set(r for r, _, _ in tenant_requests)
               | set(r for r, _, _ in expired)
               | set(r for r, _ in tenant_queue))
    out = {}
    for rkey in (sorted(routers, key=lambda r: (r is not None, str(r)))
                 if by_router else [None]):
        def _mine(k):
            return by_router is False or k == rkey
        b_counts = [0] * (len(ROUTER_BATCH_BUCKETS) + 1)
        b_sum, b_count = 0.0, 0
        for r, b in batch.items():
            if _mine(r):
                b_counts = [a + c for a, c in zip(b_counts, b["counts"])]
                b_sum += b["sum"]
                b_count += b["count"]
        depths = [v for r, v in queue_depth.items() if _mine(r)]
        merged_slow = sorted(
            (e for r, top in slow.items() if _mine(r) for e in top),
            key=lambda e: -e[0])[:ROUTER_SLOW_K]
        tmap = {}
        for (r, t, o), n in tenant_requests.items():
            if _mine(r):
                d = tmap.setdefault(t, {})
                d[o] = d.get(o, 0) + n
        emap = {}
        for (r, t, w), n in expired.items():
            if _mine(r):
                d = emap.setdefault(w, {})
                d[t] = d.get(t, 0) + n
        tq = {}
        for (r, t), v in tenant_queue.items():
            if _mine(r):
                tq[t] = tq.get(t, 0.0) + v
        ent = {
            "requests": _sum_by(requests, _mine),
            "batch_counts": b_counts, "batch_count": b_count,
            "batch_sum": b_sum,
            "queue_depth": sum(depths) if depths else None,
            "inflight": _sum_by(inflight, _mine),
            "retries": _sum_by(retries, _mine),
            "tenants": tmap, "expired": emap,
            "tenant_queue_depth": tq,
            "slow_requests": [{"latency_s": lat, "trace": tr,
                               "tenant": tn}
                              for lat, tr, tn in merged_slow]}
        if not by_router:
            return ent
        out[rkey] = ent
    return out


def _sum_by(pairs, mine):
    out = {}
    for (r, k), n in pairs.items():
        if mine(r):
            out[k] = out.get(k, 0) + n
    return out


def clear_router():
    with _ROUTER_LOCK:
        global _ROUTER
        _ROUTER = _fresh_router_state()


def _counts_histogram(name, buckets, counts, total, hsum,
                      labels=None):
    """Prometheus histogram dict from PRE-BUCKETED per-bucket counts.
    The single home of the cumulative encoding (bucket counts must
    never run ahead of the +Inf total, or consumers reject the
    series) — _histogram and the router batch histogram both ride it."""
    cum, running = [], 0
    for le, n in zip(buckets, counts):
        running += int(n)
        cum.append(["%g" % le, running])
    cum.append(["+Inf", int(total)])
    return {"name": name, "labels": dict(labels or {}),
            "buckets": cum, "sum": float(hsum), "count": int(total)}


def _histogram(name, values, buckets, labels=None):
    values = [float(v) for v in values]
    counts = []
    prev = None
    for le in buckets:
        counts.append(sum(1 for v in values
                          if v <= le and (prev is None or v > prev)))
        prev = le
    return _counts_histogram(name, buckets, counts, len(values),
                             sum(values), labels=labels)


def metrics(event_list=None, by_host=False):
    """Aggregate the bounded event log into Prometheus-style counters and
    histograms.

    Returns a JSON-ready dict ``{"counters": [...], "histograms": [...]}``
    where each counter is ``{"name", "labels", "value"}`` and each
    histogram carries cumulative ``buckets`` ([le, count] pairs ending at
    "+Inf"), ``sum`` and ``count``. Series:

      <prefix>_events_total{kind=...}        every event kind (faults,
                                             retries, restarts, sheds,
                                             restores, stragglers, ...)
      <prefix>_faults_total{point=,fault=}   injected/observed faults by
                                             injection point and kind
      <prefix>_feed_rebalance_total          data-plane lane re-maps on
                                             membership change (emitted
                                             only once any occurred)
      <prefix>_feed_epoch{host=}             gauge: slowest owned feed
                                             lane's epoch per host
      <prefix>_feed_stream_lag{host=}        gauge: committed samples a
                                             host's feed streams trail
                                             the most-advanced host
      <prefix>_transport_reconnects_total    socket-coordinator client
                                             reconnects (emitted only
                                             once any occurred)
      <prefix>_transport_failovers_total     client endpoint failovers
                                             that reached a serving
                                             (promoted) coordination
                                             member (emitted only once
                                             any occurred)
      <prefix>_transport_heartbeat_lag{host=}  gauge: seconds a host's
                                             liveness heartbeat cadence
                                             is running behind (0 when
                                             healthy)
      <prefix>_transport_term{host=}         gauge: the replication
                                             term last observed (per
                                             client host; the unlabeled
                                             series is the server's own
                                             promote/demote view) — a
                                             host pinned BELOW the
                                             others is talking to a
                                             stale ex-primary
      <prefix>_transport_replication_lag     gauge: ops the furthest-
                                             behind in-sync standby
                                             trails the primary
      <prefix>_collective_bytes_total{kind=} raw-vs-wire bytes of the
      <prefix>_stateship_bytes_total{kind=}  block-quantized gradient
      <prefix>_ckpt_bytes_total{kind=}       all-reduce / elastic state
                                             ship / checkpoint payloads
                                             (kind="raw" is what the
                                             uncompressed path would
                                             move; kind="wire" what
                                             actually moved — the pair
                                             makes compression ratios
                                             assertable, see
                                             record_bytes)
      <prefix>_router_requests_total{outcome=}  serving-fleet router
                                             requests by terminal
                                             outcome (ok/shed/deadline/
                                             error — cumulative process
                                             counters, see
                                             record_router_request)
      <prefix>_router_retries_total{replica=}  failed dispatch attempts
                                             retried on a sibling
                                             (cumulative — load-driven
                                             5xx retries run at request
                                             rate and must not ride the
                                             bounded event log)
      <prefix>_router_queue_depth            gauge: requests waiting in
                                             the router's coalescing
                                             queue
      <prefix>_router_replica_inflight{replica=}  gauge: batches the
                                             router has in flight at
                                             each replica
      <prefix>_router_batch_size             histogram: requests
                                             coalesced per dispatched
                                             micro-batch
      <prefix>_restore_latency_seconds       checkpoint-restore wall time
                                             (from restore events'
                                             latency_s)
      <prefix>_buddy_snapshot_bytes_total{kind=}  raw-vs-wire bytes of
                                             the buddy-checkpoint tier's
                                             window snapshots (rides the
                                             same record_bytes channel
                                             discipline as the pairs
                                             above)
      <prefix>_buddy_restore_total{outcome=} buddy-restore attempts by
                                             outcome (ok, or the typed
                                             disk-fallback reason:
                                             buddy_missing/buddy_stale/
                                             buddy_and_host_lost/
                                             snapshot_torn)
      <prefix>_buddy_generation{host=}       gauge: the buddy-snapshot
                                             generation each host last
                                             published (strict probes
                                             compare these across live
                                             hosts)

    The result dict also carries a ``gauges`` list (same shape as
    counters) for the feed-plane last-value series.

    ``metrics_text()`` renders the exposition format; a scraper
    sidecar/pushgateway can serve it as-is (or pull it live from
    :func:`serve_metrics`). Pass ``event_list`` to aggregate a snapshot
    instead of the live log. ``by_host=True`` additionally labels the
    event counters with the per-host tags :func:`context` attached
    (``{kind=...,host=...}``) so one pod-wide scrape still tells the
    hosts apart; events recorded outside a host context keep the plain
    ``{kind=...}`` series."""
    evs = _LOG.events() if event_list is None else list(event_list)
    if by_host:
        kind_counts = collections.Counter(
            (e["kind"], e.get("host")) for e in evs)
        counters = [
            {"name": METRIC_PREFIX + "_events_total",
             "labels": {"kind": kind} if host is None
             else {"kind": kind, "host": str(host)}, "value": n}
            for (kind, host), n in sorted(
                kind_counts.items(),
                key=lambda kv: (kv[0][0], str(kv[0][1])))]
    else:
        kind_counts = collections.Counter(e["kind"] for e in evs)
        counters = [
            {"name": METRIC_PREFIX + "_events_total",
             "labels": {"kind": kind}, "value": n}
            for kind, n in sorted(kind_counts.items())]
    fault_counts = collections.Counter(
        (e.get("point", "?"), e.get("fault", "?"))
        for e in evs if e["kind"] == "fault")
    counters += [
        {"name": METRIC_PREFIX + "_faults_total",
         "labels": {"point": p, "fault": f}, "value": n}
        for (p, f), n in sorted(fault_counts.items())]
    # feed-plane series (elastic data plane): emitted only when the
    # corresponding events exist, so feed-less jobs export nothing new
    n_rebalance = sum(1 for e in evs if e["kind"] == "feed_rebalance")
    if n_rebalance:
        counters.append({"name": METRIC_PREFIX + "_feed_rebalance_total",
                         "labels": {}, "value": n_rebalance})
    # transport series (socket coordinator): reconnect attempts are a
    # counter; the heartbeat cadence lag is a per-host last-value gauge
    n_reconnect = sum(1 for e in evs
                      if e["kind"] == "transport_reconnect")
    if n_reconnect:
        counters.append(
            {"name": METRIC_PREFIX + "_transport_reconnects_total",
             "labels": {}, "value": n_reconnect})
    # coordination-plane HA: failovers are the headline counter (a
    # SIGKILLed primary costs exactly one per client, not an abort)
    n_failover = sum(1 for e in evs
                     if e["kind"] == "transport_failover")
    if n_failover:
        counters.append(
            {"name": METRIC_PREFIX + "_transport_failovers_total",
             "labels": {}, "value": n_failover})
    # compressed-movement byte accounting (quantized collectives, elastic
    # state ship, checkpoint payloads): raw-vs-wire counter pairs from the
    # cumulative process counters — emitted only for channels that moved
    # bytes, so jobs without the compression paths export nothing new.
    # NB: these ride the live counters even for event_list snapshots
    # (they are not events — snapshotting them is bytes_totals()).
    for ch, tot in sorted(bytes_totals().items()):
        for kind in ("raw", "wire"):
            counters.append(
                {"name": "%s_%s_bytes_total" % (METRIC_PREFIX, ch),
                 "labels": {"kind": kind}, "value": tot[kind]})
    # trace-time kernel-selection decisions (pallas_dispatch.choose):
    # cumulative process counters like the byte pairs — emitted only
    # once a compile made a decision, so pallas-less jobs export
    # nothing new
    for (op, impl, source), n in sorted(kernel_choice_totals().items()):
        counters.append(
            {"name": METRIC_PREFIX + "_kernel_choice_total",
             "labels": {"op": op, "impl": impl, "source": source},
             "value": n})
    # program-verifier diagnostics (framework/analysis.py): cumulative
    # per-(pass, severity) counters — emitted only once a verification
    # produced diagnostics, so clean jobs export nothing new
    for (pass_name, severity), n in sorted(analysis_totals().items()):
        counters.append(
            {"name": METRIC_PREFIX + "_analysis_diagnostics_total",
             "labels": {"pass": pass_name, "severity": severity},
             "value": n})
    # serving-fleet router series (cumulative process counters like the
    # byte pairs — NOT events; see record_router_request): emitted only
    # once the router did anything, so router-less jobs export nothing
    # new. Counter: requests by terminal outcome. Gauges: queue depth +
    # per-replica in-flight. Histogram: coalesced batch size. Every
    # series is per-ROUTER (router= label) so N concurrent routers in
    # one process never overwrite each other; the unlabeled series is
    # the single-router/legacy shape.
    by_router = router_totals(by_router=True)

    def _rlbl(rkey, **extra):
        lbl = dict(extra)
        if rkey is not None:
            lbl["router"] = rkey
        return lbl

    router_hists = []
    for rkey, rt in by_router.items():
        counters += [
            {"name": METRIC_PREFIX + "_router_requests_total",
             "labels": _rlbl(rkey, outcome=outcome), "value": n}
            for outcome, n in sorted(rt["requests"].items())]
        counters += [
            {"name": METRIC_PREFIX + "_router_retries_total",
             "labels": _rlbl(rkey, replica=str(r)), "value": n}
            for r, n in sorted(rt["retries"].items())]
        # QoS additions: per-tenant outcome counters alongside the
        # aggregate (never instead of it — the aggregate above is the
        # tenant-less deployment's exact historical series), plus the
        # deadline-budget-expiry counters by catch point
        counters += [
            {"name": METRIC_PREFIX + "_router_requests_total",
             "labels": _rlbl(rkey, outcome=outcome, tenant=t),
             "value": n}
            for t, by_out in sorted(rt["tenants"].items())
            for outcome, n in sorted(by_out.items())]
        counters += [
            {"name": METRIC_PREFIX + "_router_deadline_expired_total",
             "labels": _rlbl(rkey, where=where, tenant=t), "value": n}
            for where, by_t in sorted(rt["expired"].items())
            for t, n in sorted(by_t.items())]
        if rt["batch_count"]:
            router_hists.append(_counts_histogram(
                METRIC_PREFIX + "_router_batch_size",
                ROUTER_BATCH_BUCKETS, rt["batch_counts"],
                rt["batch_count"], rt["batch_sum"],
                labels=_rlbl(rkey)))
    last_epoch, last_lag, last_hb = {}, {}, {}
    last_term, last_repl_lag = {}, {}
    last_lterm, last_target = {}, {}
    for e in evs:
        if e["kind"] == "feed_epoch":
            last_epoch[e.get("host")] = e.get("epoch", 0)
        elif e["kind"] == "feed_lag":
            last_lag[e.get("host")] = e.get("lag", 0)
        elif e["kind"] == "transport_hb_lag":
            last_hb[e.get("host")] = e.get("lag_s", 0.0)
        elif e["kind"] in ("transport_term", "transport_promote"):
            # per-client-host term views, plus the server's own
            # (unlabeled) promote/demote view: a host whose gauge sits
            # below the others is still trusting a stale ex-primary
            last_term[e.get("host")] = e.get("term", 0)
        elif e["kind"] == "transport_repl_lag":
            last_repl_lag[e.get("host")] = e.get("lag", 0)
        elif e["kind"] == "fleet_leader_term":
            # per-router admission-leader term views (the router-tier
            # twin of transport_term): a router pinned below its peers
            # is still trusting a stale ex-leader
            last_lterm[e.get("router")] = e.get("term", 0)
        elif e["kind"] == "fleet_autoscale":
            # last autoscale decision's target replica count
            last_target[None] = e.get("target", 0)
    gauges = []
    for name, series, label in (
            (METRIC_PREFIX + "_feed_epoch", last_epoch, "host"),
            (METRIC_PREFIX + "_feed_stream_lag", last_lag, "host"),
            (METRIC_PREFIX + "_transport_heartbeat_lag", last_hb,
             "host"),
            (METRIC_PREFIX + "_transport_term", last_term, "host"),
            (METRIC_PREFIX + "_transport_replication_lag",
             last_repl_lag, "host"),
            (METRIC_PREFIX + "_fleet_leader_term", last_lterm,
             "router"),
            (METRIC_PREFIX + "_fleet_target_replicas", last_target,
             "router")):
        gauges += [{"name": name,
                    "labels": {} if h is None else {label: str(h)},
                    "value": v}
                   for h, v in sorted(series.items(),
                                      key=lambda kv: str(kv[0]))]
    for rkey, rt in by_router.items():
        if rt["queue_depth"] is not None:
            gauges.append(
                {"name": METRIC_PREFIX + "_router_queue_depth",
                 "labels": _rlbl(rkey), "value": rt["queue_depth"]})
        gauges += [{"name": METRIC_PREFIX + "_router_replica_inflight",
                    "labels": _rlbl(rkey, replica=str(r)), "value": v}
                   for r, v in sorted(rt["inflight"].items())]
        gauges += [{"name": METRIC_PREFIX + "_router_tenant_queue_depth",
                    "labels": _rlbl(rkey, tenant=t), "value": v}
                   for t, v in sorted(rt["tenant_queue_depth"].items())]
    # elastic pp re-cut (stage re-stacking over a shrunk mesh): the
    # re-cut counter, the last re-cut's retarget wall, and the CURRENT
    # slot count + live-host pair (both from the last pp retarget
    # event — re-grow moves them back) — emitted only for pods that
    # ever re-cut, so plain pods export nothing new. serving_probe
    # --strict cross-checks pp_slots against pp_live_hosts: more slots
    # than surviving hosts means a torn re-cut.
    recut_evs = [e for e in evs if e["kind"] == "elastic_pp_recut"]
    if recut_evs:
        counters.append({"name": METRIC_PREFIX + "_pp_recut_total",
                         "labels": {}, "value": len(recut_evs)})
        last_ms = next((1000.0 * float(e["latency_s"])
                        for e in reversed(recut_evs)
                        if "latency_s" in e), None)
        if last_ms is not None:
            gauges.append({"name": METRIC_PREFIX + "_pp_recut_ms",
                           "labels": {}, "value": round(last_ms, 3)})
    last_pp = next((e for e in reversed(evs)
                    if "pp_slots" in e
                    and e["kind"] in ("elastic_pp_recut",
                                      "elastic_grow")), None)
    if last_pp is not None:
        gauges.append({"name": METRIC_PREFIX + "_pp_slots",
                       "labels": {}, "value": int(last_pp["pp_slots"])})
        cap = str(last_pp.get("capacity", "")).partition("/")[0]
        if cap.isdigit():
            gauges.append({"name": METRIC_PREFIX + "_pp_live_hosts",
                           "labels": {}, "value": int(cap)})
    restore_lat = [e["latency_s"] for e in evs
                   if e["kind"] == "restore" and "latency_s" in e]
    histograms = [_histogram(METRIC_PREFIX + "_restore_latency_seconds",
                             restore_lat, RESTORE_LATENCY_BUCKETS)]
    histograms += router_hists
    # executor step-phase latency (the obs layer's always-on metrics
    # half): per-kind histograms from the cumulative process counters —
    # emitted only for phases that ran, so executor-less jobs export
    # nothing new
    for kind, h in sorted(executor_step_totals().items()):
        if h["count"]:
            histograms.append(_counts_histogram(
                METRIC_PREFIX + "_executor_step_seconds",
                EXEC_STEP_BUCKETS, h["counts"], h["count"], h["sum"],
                labels={"kind": kind}))
    # failpoint plane (framework/faultinject.py): fired-hit counters by
    # site plus an armed gauge — emitted only when something armed or
    # fired, so production processes export nothing new; when anything
    # IS exported, serving_probe --strict refuses the scrape on
    # armed=1 (live failpoints have no business in production)
    from . import faultinject
    counters += [
        {"name": METRIC_PREFIX + "_failpoint_hits_total",
         "labels": {"site": site}, "value": n}
        for site, n in sorted(faultinject.hits_total().items())]
    if faultinject.armed() or faultinject.hits_total():
        gauges.append(
            {"name": METRIC_PREFIX + "_faultinject_armed",
             "labels": {}, "value": 1 if faultinject.armed() else 0})
    # numeric-fault recovery (BuildStrategy numeric_policy): one
    # counter per (policy, culprit) from the numeric_fault events —
    # the chaos battery and serving_probe assert on the culprit label
    nf_counts = collections.Counter(
        (e.get("policy", "?"), e.get("culprit", "?"))
        for e in evs if e["kind"] == "numeric_fault")
    counters += [
        {"name": METRIC_PREFIX + "_numeric_fault_total",
         "labels": {"policy": p, "culprit": c}, "value": n}
        for (p, c), n in sorted(nf_counts.items())]
    # buddy-checkpoint tier (framework/buddy.py): restore outcomes by
    # label plus the per-host last-published-generation gauge — emitted
    # only for pods that ever ran the buddy tier, so plain jobs export
    # nothing new. serving_probe --strict compares the generation
    # gauges across live hosts (divergence > 1 window = some host's
    # snapshots are not landing).
    br_counts = collections.Counter(
        e.get("outcome", "?") for e in evs
        if e["kind"] == "buddy_restore")
    counters += [
        {"name": METRIC_PREFIX + "_buddy_restore_total",
         "labels": {"outcome": o}, "value": n}
        for o, n in sorted(br_counts.items())]
    gauges += [
        {"name": METRIC_PREFIX + "_buddy_generation",
         "labels": {"host": str(h)}, "value": g}
        for h, g in sorted(buddy_gens().items())]
    # p2p mailbox gauges: residency per mailbox host (the coordinator's
    # row, host="coord", is the memory-ceiling gate serving_probe
    # --strict enforces), the last send's delta wire ratio, and the
    # last host-to-host pull latency. Nothing recorded -> nothing
    # exported.
    gauges += [
        {"name": METRIC_PREFIX + "_buddy_resident_bytes",
         "labels": {"host": str(h)}, "value": b}
        for h, b in sorted(buddy_resident().items())]
    if buddy_delta_ratio() is not None:
        gauges.append({"name": METRIC_PREFIX + "_buddy_delta_ratio",
                       "labels": {}, "value": buddy_delta_ratio()})
    if buddy_fetch_ms() is not None:
        gauges.append({"name": METRIC_PREFIX + "_buddy_p2p_fetch_ms",
                       "labels": {}, "value": buddy_fetch_ms()})
    # span-ring overflow (obs tentpole): dropped spans mean a merged
    # timeline is LYING about what happened — exported whenever the
    # engine is on (0 = trustworthy) or anything was ever dropped, so
    # serving_probe --strict can gate on it; tracing-off jobs export
    # nothing new
    from . import obs
    if obs.enabled() or obs.dropped_total():
        counters.append(
            {"name": METRIC_PREFIX + "_trace_spans_dropped_total",
             "labels": {}, "value": obs.dropped_total()})
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}


def _escape_label_value(v):
    """Prometheus exposition escaping for label VALUES: backslash,
    double quote and newline (in that order — escaping the escape
    first keeps it reversible). An unescaped quote in, say, a
    replica-address label would tear the sample line into invalid
    exposition text that every scraper rejects."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _unescape_label_value(v):
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            out.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt,
                                                            c + nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _fmt_labels(labels):
    if not labels:
        return ""
    return "{%s}" % ",".join(
        '%s="%s"' % (k, _escape_label_value(v))
        for k, v in sorted(labels.items()))


def metrics_text(m=None):
    """Render :func:`metrics` in the Prometheus text exposition format."""
    m = m if m is not None else metrics()
    lines = []
    seen_type = set()
    for c in m["counters"]:
        if c["name"] not in seen_type:
            seen_type.add(c["name"])
            lines.append("# TYPE %s counter" % c["name"])
        lines.append("%s%s %g" % (c["name"], _fmt_labels(c["labels"]),
                                  c["value"]))
    for g in m.get("gauges", ()):
        if g["name"] not in seen_type:
            seen_type.add(g["name"])
            lines.append("# TYPE %s gauge" % g["name"])
        lines.append("%s%s %g" % (g["name"], _fmt_labels(g["labels"]),
                                  g["value"]))
    for h in m["histograms"]:
        lines.append("# TYPE %s histogram" % h["name"])
        for le, n in h["buckets"]:
            labels = dict(h["labels"], le=le)
            lines.append("%s_bucket%s %d" % (h["name"],
                                             _fmt_labels(labels), n))
        lines.append("%s_sum%s %g" % (h["name"], _fmt_labels(h["labels"]),
                                      h["sum"]))
        lines.append("%s_count%s %d" % (h["name"],
                                        _fmt_labels(h["labels"]),
                                        h["count"]))
    return "\n".join(lines) + "\n"


def parse_metrics_text(text):
    """Parse a text exposition back into ``[(name, labels, value)]`` —
    the round-trip half used by tests and by scrapers that want the
    samples without a Prometheus client library."""
    import re
    # label values are quoted strings with \\, \" and \n escapes (see
    # _escape_label_value) — the blob/value regexes must track quoting
    # or a value containing '}' / '"' tears the parse
    label_val = r'"(?:[^"\\]|\\.)*"'
    line_re = re.compile(
        r'^([A-Za-z_:][\w:]*)(\{(?:[^"{}]|%s)*\})?\s+(\S+)$'
        % label_val)
    pair_re = re.compile(r'(\w+)=(%s)' % label_val)
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = line_re.match(line)
        if not m:
            raise ValueError("unparsable metrics line: %r" % line)
        name, labelblob, value = m.groups()
        labels = {}
        if labelblob:
            for k, quoted in pair_re.findall(labelblob):
                labels[k] = _unescape_label_value(quoted[1:-1])
        samples.append((name, labels, float(value)))
    return samples


class MetricsServer(object):
    """A tiny stdlib HTTP listener serving the live metrics exposition.

    ``GET /metrics`` renders ``metrics_text(metrics(by_host=True))`` at
    request time — per-host labels ride the :func:`context` tags — and
    ``GET /healthz`` answers 200 (liveness). Runs on a daemon thread;
    :meth:`close` shuts it down. Start one via :func:`serve_metrics`.
    """

    def __init__(self, port=0, host="127.0.0.1"):
        import http.server

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):   # noqa: N802 - stdlib naming
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = metrics_text(metrics(by_host=True)).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_error(404, "try /metrics")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):   # scrapes are not log lines
                pass

        self._server = http.server.ThreadingHTTPServer((host, port),
                                                       _Handler)
        self.host, self.port = self._server.server_address[:2]
        self.url = "http://%s:%d/metrics" % (self.host, self.port)
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="paddle_tpu-metrics-%d" % self.port)
        self._thread.start()

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def serve_metrics(port=0, host="127.0.0.1"):
    """Start the metrics pull endpoint (Prometheus text exposition at
    ``/metrics``, per-host labels from :func:`context` tags).

    ``port=0`` binds an ephemeral port — read it back from the returned
    server's ``.port``/``.url``. The listener renders the live event
    log on every scrape, so there is nothing to push and nothing goes
    stale; ``tools/serving_probe.py --metrics-url`` knows how to scrape
    it. Call ``.close()`` (or use as a context manager) to stop."""
    server = MetricsServer(port=port, host=host)
    record_event("metrics_serve", url=server.url)
    return server


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

# point -> kinds it accepts (parse-time validation: a typo'd chaos spec
# must fail loudly at configure time, not silently never fire)
_POINT_KINDS = {
    "step": ("preempt", "collective_timeout", "nan", "die"),
    "ckpt_write": ("io_error",),
    "serve": ("slow", "error"),
}


class FaultSpec(object):
    """One parsed fault: ``point:kind[=arg][@N | ~p]``.

    ``@N``  fire exactly at the N-th call of the point (1-based, default 1)
    ``~p``  fire each call with probability p (seeded — deterministic)
    ``=arg`` float argument (e.g. ``serve:slow=2.0`` sleeps 2 seconds)
    """

    def __init__(self, point, kind, at=None, prob=None, arg=None):
        if point not in _POINT_KINDS:
            raise ValueError("unknown injection point %r (have %s)"
                             % (point, sorted(_POINT_KINDS)))
        if kind not in _POINT_KINDS[point]:
            raise ValueError("injection point %r has no fault kind %r "
                             "(have %s)" % (point, kind,
                                            _POINT_KINDS[point]))
        self.point, self.kind, self.arg = point, kind, arg
        self.at = at if prob is not None or at is not None else 1
        self.prob = prob

    @classmethod
    def parse(cls, text):
        text = text.strip()
        if ":" not in text:
            raise ValueError("fault spec %r needs the form "
                             "point:kind[=arg][@N|~p]" % text)
        point, rest = text.split(":", 1)
        at = prob = arg = None
        if "@" in rest:
            rest, n = rest.rsplit("@", 1)
            at = int(n)
        elif "~" in rest:
            rest, p = rest.rsplit("~", 1)
            prob = float(p)
        if "=" in rest:
            rest, a = rest.split("=", 1)
            arg = float(a)
        return cls(point.strip(), rest.strip(), at=at, prob=prob, arg=arg)

    def __repr__(self):
        tail = "@%d" % self.at if self.prob is None else "~%g" % self.prob
        arg = "" if self.arg is None else "=%g" % self.arg
        return "FaultSpec(%s:%s%s%s)" % (self.point, self.kind, arg, tail)


class FaultInjector(object):
    """Deterministic chaos harness.

    Configure with a spec string (``;`` or ``,`` separated FaultSpecs) or
    a list of FaultSpec objects, plus a seed for probabilistic specs.
    Production code calls :func:`fire` at its injection points; with no
    injector installed that is a near-free no-op."""

    def __init__(self, specs="", seed=0):
        if isinstance(specs, str):
            parts = [s for chunk in specs.split(";")
                     for s in chunk.split(",") if s.strip()]
            self.specs = [FaultSpec.parse(s) for s in parts]
        else:
            self.specs = list(specs)
        self.seed = seed
        self._rng = random.Random(seed)
        self._counts = {}
        self._lock = threading.Lock()

    def counts(self):
        """{point: number of fire() calls seen} — test introspection."""
        with self._lock:
            return dict(self._counts)

    def fire(self, point, what=""):
        """Evaluate the specs for ``point`` at this call.

        Raises the fault's error for raising kinds; returns an action
        dict (e.g. ``{"slow_s": 2.0}``) for behavioral kinds."""
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            hits = []
            for spec in self.specs:
                if spec.point != point:
                    continue
                if spec.prob is not None:
                    if self._rng.random() >= spec.prob:
                        continue
                elif spec.at != n:
                    continue
                hits.append(spec)
        actions = {}
        for spec in hits:
            record_event("fault", point=point, fault=spec.kind, call=n,
                         what=what)
            if spec.kind == "preempt":
                raise SimulatedPreemptionError(
                    "injected preemption at %s call %d%s"
                    % (point, n, (" (%s)" % what) if what else ""))
            if spec.kind == "die":
                raise SimulatedHostDeathError(
                    "injected host death at %s call %d%s"
                    % (point, n, (" (%s)" % what) if what else ""))
            if spec.kind == "collective_timeout":
                raise CollectiveTimeoutError(
                    "injected collective timeout at %s call %d" % (point, n))
            if spec.kind == "nan":
                raise FloatingPointError(
                    "injected NaN blowup at %s call %d" % (point, n))
            if spec.kind == "io_error":
                raise OSError(
                    "injected checkpoint I/O error at %s call %d"
                    % (point, n))
            if spec.kind == "error":
                raise RuntimeError(
                    "injected serving failure at %s call %d" % (point, n))
            if spec.kind == "slow":
                actions["slow_s"] = spec.arg if spec.arg is not None else 1.0
        return actions


_state = {"injector": None, "env_loaded": False}


def install(injector):
    """Install an injector globally (None uninstalls). Returns it."""
    _state["injector"] = injector
    _state["env_loaded"] = True   # explicit install wins over env
    return injector


def current_injector():
    if _state["injector"] is None and not _state["env_loaded"]:
        _state["env_loaded"] = True
        spec = os.environ.get("PADDLE_TPU_FAULTS", "")
        if spec:
            # the env var is shared with framework/faultinject.py:
            # dotted-site specs ("transport.send:raise@3") belong to
            # the failpoint plane; only bare legacy points are ours
            parts = [s for chunk in spec.split(";")
                     for s in chunk.split(",") if s.strip()]
            legacy = [s for s in parts
                      if "." not in s.strip().split(":", 1)[0]]
            if legacy:
                seed = int(os.environ.get("PADDLE_TPU_FAULT_SEED",
                                          "0") or 0)
                _state["injector"] = FaultInjector(",".join(legacy),
                                                   seed=seed)
    return _state["injector"]


def reload_env():
    """Drop the cached env injector and re-read PADDLE_TPU_FAULTS."""
    _state["injector"] = None
    _state["env_loaded"] = False
    return current_injector()


@contextlib.contextmanager
def inject(specs, seed=0):
    """Context manager: install a FaultInjector for the enclosed block."""
    inj = specs if isinstance(specs, FaultInjector) \
        else FaultInjector(specs, seed=seed)
    old_inj, old_env = _state["injector"], _state["env_loaded"]
    _state["injector"], _state["env_loaded"] = inj, True
    try:
        yield inj
    finally:
        _state["injector"], _state["env_loaded"] = old_inj, old_env


def fire(point, what=""):
    """Production injection hook — a no-op unless an injector is
    installed (or PADDLE_TPU_FAULTS is set)."""
    inj = current_injector()
    if inj is None:
        return {}
    return inj.fire(point, what=what)


# ---------------------------------------------------------------------------
# silent-data-corruption suspicion
# ---------------------------------------------------------------------------

class SDCDetector(object):
    """Per-host gradient-norm outlier detection — the SDC tripwire.

    A host with a flaky ALU produces gradients that are WRONG but
    finite, so no finite-mask sees them; what does show is that host's
    gradient norm drifting away from its peers on identical replicated
    math. Feed one scalar per host per observation window (the pod
    gathers them anyway for its window verdicts); a host whose
    robust deviation from the pod median

        |x_h - median(x)| / (MAD(x) + eps)

    exceeds ``threshold`` for ``consecutive`` windows in a row within
    the sliding ``window`` is flagged a suspect exactly once, a
    ``sdc_suspect`` event is recorded, and the caller hands it to the
    drain path (ElasticTrainer host drain). Median/MAD (not mean/std)
    so the corrupt host's own wild values cannot mask themselves, and
    a single-step spike (a legitimate loss blip hits EVERY host's norm
    together) never trips the consecutive gate."""

    def __init__(self, threshold=6.0, consecutive=3, window=32,
                 eps=1e-12):
        if consecutive < 1:
            raise ValueError("consecutive must be >= 1")
        self.threshold = float(threshold)
        self.consecutive = int(consecutive)
        self.window = int(window)
        self.eps = float(eps)
        self._streak = {}      # host -> consecutive outlier windows
        self._history = collections.deque(maxlen=self.window)
        self._suspects = set()
        self._lock = threading.Lock()

    def observe(self, norms, step=None):
        """One observation window: ``{host: grad_norm}``. Returns the
        list of NEWLY flagged suspect hosts (usually empty)."""
        vals = {h: float(v) for h, v in norms.items()}
        if len(vals) < 3:
            return []   # a median of 2 cannot tell who is wrong
        xs = sorted(vals.values())
        mid = len(xs) // 2
        med = xs[mid] if len(xs) % 2 else 0.5 * (xs[mid - 1] + xs[mid])
        devs = sorted(abs(v - med) for v in xs)
        mad = devs[mid] if len(devs) % 2 \
            else 0.5 * (devs[mid - 1] + devs[mid])
        new = []
        with self._lock:
            self._history.append(dict(vals))
            for h, v in vals.items():
                score = abs(v - med) / (mad + self.eps)
                # a non-finite norm is an outlier by definition (the
                # numeric policy handles the step; the detector only
                # counts the host's streak)
                outlier = score > self.threshold or v != v
                self._streak[h] = self._streak.get(h, 0) + 1 \
                    if outlier else 0
                if self._streak[h] >= self.consecutive \
                        and h not in self._suspects:
                    self._suspects.add(h)
                    new.append(h)
                    record_event("sdc_suspect", host_suspect=str(h),
                                 score=round(score, 3),
                                 streak=self._streak[h],
                                 **({} if step is None
                                    else {"step": int(step)}))
        return new

    def suspects(self):
        with self._lock:
            return set(self._suspects)

    def clear(self, host=None):
        """Forget a drained-and-replaced host (or everything)."""
        with self._lock:
            if host is None:
                self._suspects.clear()
                self._streak.clear()
                self._history.clear()
            else:
                self._suspects.discard(host)
                self._streak.pop(host, None)


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------

# Transient: the operation may succeed on replay from a clean state —
# hung/injected collectives, preemptions, torn I/O, NaN blowups (restore
# rewinds past the poisoned state; a deterministic NaN re-fires and the
# restart budget converts it to a hard failure).
_TRANSIENT_TYPES = (CollectiveTimeoutError, SimulatedPreemptionError,
                    ServerOverloadedError, OSError, TimeoutError,
                    ConnectionError, FloatingPointError)
# Fatal: program-shape bugs — shape/sharding/dtype mismatches replay
# identically, so retrying only burns the budget.
_FATAL_TYPES = (ValueError, TypeError, KeyError, IndexError,
                NotImplementedError, AssertionError)


def classify(err):
    """'transient' (worth a retry/restore) or 'fatal' (re-raise now)."""
    if isinstance(err, _FATAL_TYPES):
        return "fatal"
    if isinstance(err, _TRANSIENT_TYPES):
        return "transient"
    return "fatal"


class RetryPolicy(object):
    """Exponential backoff with (seeded, deterministic) jitter.

    delay(attempt) = min(base * multiplier**attempt, max) * U[1-jitter, 1]
    """

    def __init__(self, max_attempts=4, base_delay_s=0.05, max_delay_s=5.0,
                 multiplier=2.0, jitter=0.5, seed=0, sleep=time.sleep,
                 classify=classify):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.sleep = sleep
        self._classify = classify
        self._rng = random.Random(seed)

    def is_transient(self, err):
        return self._classify(err) == "transient"

    def delay_s(self, attempt):
        """Backoff before retry number ``attempt`` (0-based)."""
        d = min(self.base_delay_s * self.multiplier ** attempt,
                self.max_delay_s)
        if self.jitter:
            d *= 1.0 - self.jitter * self._rng.random()
        return d

    def call(self, fn, *args, **kwargs):
        """Run ``fn`` with transient-retry; fatal errors raise through.
        ``what=`` names the operation in events."""
        what = kwargs.pop("what", getattr(fn, "__name__", "operation"))
        last = None
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                last = e
                if not self.is_transient(e) \
                        or attempt + 1 >= self.max_attempts:
                    raise
                d = self.delay_s(attempt)
                record_event("retry", what=what, attempt=attempt + 1,
                             error=type(e).__name__, backoff_s=d)
                self.sleep(d)
        raise last   # pragma: no cover - loop always returns or raises


# ---------------------------------------------------------------------------
# deadline helper (serving)
# ---------------------------------------------------------------------------

def run_with_deadline(fn, deadline_s, what="request"):
    """Run ``fn()`` with a wall-clock bound.

    Shares watchdog.bounded_call with wait_with_timeout — the same
    detect-the-hang mechanism, lifted from device waits to arbitrary
    host work (injected slowness, cold-bucket compiles). The work
    itself cannot be cancelled; the CALLER gets
    control back with a DeadlineExceededError and the orphaned thread
    finishes (and warms any compile cache) in the background."""
    if deadline_s is None:
        return fn()
    done, value, err = bounded_call(fn, deadline_s,
                                    name="paddle_tpu-deadline")
    if not done:
        record_event("deadline", what=what, deadline_s=float(deadline_s))
        raise DeadlineExceededError(
            "%s did not complete within its %.2fs deadline"
            % (what, float(deadline_s)))
    if err is not None:
        raise err
    return value


# ---------------------------------------------------------------------------
# resilient training
# ---------------------------------------------------------------------------

def _stack_feeds(feed_dicts):
    """[{name: per-step array}] -> {name: stacked (steps, ...) array} for
    Executor.run_steps."""
    import numpy as np
    keys = set(feed_dicts[0])
    for f in feed_dicts[1:]:
        if set(f) != keys:
            raise ValueError("all feeds in a run_steps window need the "
                             "same keys; got %s vs %s"
                             % (sorted(keys), sorted(f)))
    return {k: np.stack([np.asarray(f[k]) for f in feed_dicts])
            for k in keys}


class ResilientTrainer(object):
    """Auto-recovering training driver.

    Wraps Executor.run / run_steps (plain Program OR CompiledProgram —
    the latter's collective-timeout watchdog raises into the same
    handler): steps run in dispatch windows, the whole scope is
    checkpointed every ``checkpoint_every`` steps, and a transient
    failure (see :func:`classify`) triggers backoff -> restore of the
    latest VALID checkpoint (io.load_checkpoint quarantines corrupt step
    dirs) -> step-counter rewind -> replay. Because a checkpoint carries
    params, optimizer moments AND the PRNG step counter, the replayed
    trajectory is numerically identical to an uninterrupted run.

    The restart budget bounds total recoveries per run() call; a fault
    that keeps re-firing becomes RestartBudgetExceededError.
    """

    def __init__(self, executor, program, ckpt_dir, fetch_list=None,
                 checkpoint_every=10, max_restarts=3, retry_policy=None,
                 steps_per_dispatch=1, keep_last=3, scope=None,
                 async_checkpoints=False, feed=None, ckpt_compress=None):
        from .compiler import CompiledProgram
        if checkpoint_every < 1:
            raise ValueError("checkpoint_every must be >= 1")
        if steps_per_dispatch < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        self._executor = executor
        self._target = program   # what executor.run receives
        self._program = program._program \
            if isinstance(program, CompiledProgram) else program
        self._ckpt_dir = ckpt_dir
        self._fetch_list = fetch_list
        self._checkpoint_every = int(checkpoint_every)
        self._max_restarts = int(max_restarts)
        self._policy = retry_policy or RetryPolicy()
        self._steps_per_dispatch = int(steps_per_dispatch)
        self._keep_last = int(keep_last)
        # explicit scope: what lets a PodResilientTrainer give each
        # simulated host disjoint state in ONE process (None = the
        # process-global scope, the single-host default)
        self._scope = scope
        # async_checkpoints=True moves the file commit off the step path
        # (io.save_checkpoint blocking=False; single-host only)
        self._async_ckpt = bool(async_checkpoints)
        # feed: an attached reader.ShardedFeed — the trainer pulls its
        # windows from it (run(feeds=None, steps=N)), checkpoints carry
        # the feed cursor, and a restore rewinds the DATA position too,
        # so replay re-reads the exact batch sequence
        self._feed = feed
        # ckpt_compress: io.save_checkpoint(compress=) for every periodic
        # snapshot ("zlib" = lossless deflate, "q8" = lossy block codec —
        # see io.save_checkpoint; restores are transparent either way)
        self._ckpt_compress = ckpt_compress
        # numeric_policy="rewind" recovery: global batch indices whose
        # data poisoned a step — the replay after the consensus/local
        # rewind SKIPS them, so the recovered trajectory is the
        # uninterrupted no-poison-batch run, bit for bit
        self._poison_batches = set()

    # -- events convenience ------------------------------------------------
    @staticmethod
    def events(kind=None):
        return events(kind)

    def _save(self, step):
        from .. import io as io_mod
        feed_state = None if self._feed is None \
            else self._feed.global_state()
        io_mod.save_checkpoint(self._executor, self._ckpt_dir,
                               self._program, step=step,
                               keep_last=self._keep_last,
                               blocking=not self._async_ckpt,
                               scope=self._scope, feed_state=feed_state,
                               compress=self._ckpt_compress)
        record_event("ckpt", step=step)

    def _restore(self, step=None, shardings=None, feed_lags=None):
        """Restore ``step`` (pod-consensus path) or the latest valid
        checkpoint. Always joins an in-flight async commit FIRST: a
        blocking=False save still writing while we pick the restore
        point could otherwise tear the very dir we are about to read. A
        FAILED async commit is recorded, not raised — its torn step dir
        is exactly what the load's scrub/quarantine fallback handles.

        shardings: optional {var: jax.sharding.Sharding} passed through
        to io.load_checkpoint so the restore materializes straight onto
        the CURRENT mesh — what lets a checkpoint written at 8 hosts
        restore onto an elastically-shrunk 6-host topology.

        feed_lags: the pod-AGREED {host: stream lag} snapshot for the
        cursor restore's lane re-mapping (ElasticTrainer assembles it
        from the frozen window verdicts). Without it a
        weighted-rebalance feed would re-place any orphaned lanes from
        each process's LOCAL gauges — divergent maps on a socket pod.

        With a feed attached, the checkpoint's dataset cursor is
        restored into it at the same time (ownership re-mapped onto the
        feed's current live set), so the replay re-reads the exact batch
        sequence; a feed-mode checkpoint that carries no cursor is a
        FATAL FeedStateError — replaying from a wrong data position
        would silently break exactly-once."""
        from .. import io as io_mod
        t0 = time.perf_counter()
        try:
            io_mod.wait_for_pending_saves()
        except Exception as e:
            record_event("ckpt_async_error", error=type(e).__name__)
        if self._feed is not None:
            got, feed_state = io_mod.load_checkpoint(
                self._executor, self._ckpt_dir, self._program, step=step,
                scope=self._scope, shardings=shardings,
                with_feed_state=True)
            if feed_state is None:
                from ..reader.sharded_feed import FeedStateError
                raise FeedStateError(
                    "checkpoint step %s in %s carries no feed cursor but "
                    "a ShardedFeed is attached — restoring params without "
                    "the data position would re-read or skip samples"
                    % (got, self._ckpt_dir))
            self._feed.restore(feed_state, lags=feed_lags)
        else:
            got = io_mod.load_checkpoint(self._executor, self._ckpt_dir,
                                         self._program, step=step,
                                         scope=self._scope,
                                         shardings=shardings)
        got = int(got)
        record_event("restore", step=got,
                     latency_s=time.perf_counter() - t0)
        return got

    def _dispatch(self, feeds, step, w, fetch_list):
        return self._dispatch_window(feeds[step:step + w], step,
                                     fetch_list)

    def _dispatch_window(self, batches, base_step, fetch_list):
        """Dispatch one window, dropping any batch whose global index
        was marked poisoned by a numeric-fault rewind. Skipped slots
        report ``None`` fetches; the step counter still advances over
        them so the checkpoint cadence and caller indexing hold."""
        if self._poison_batches:
            keep, skipped = [], []
            for i, b in enumerate(batches):
                if base_step + i in self._poison_batches:
                    skipped.append(base_step + i)
                else:
                    keep.append(b)
            if skipped:
                for idx in skipped:
                    record_event("poison_skip", batch=idx)
                outs = iter(self._dispatch_batches(keep, fetch_list)
                            if keep else [])
                return [None if base_step + i in self._poison_batches
                        else next(outs) for i in range(len(batches))]
        return self._dispatch_batches(batches, fetch_list)

    def _dispatch_batches(self, batches, fetch_list):
        """Run one window of batch feed dicts; returns the per-batch
        fetch lists (shared by the list-driven and ShardedFeed paths)."""
        import numpy as np
        if not batches:
            return []
        if len(batches) == 1:
            return [self._executor.run(self._target, feed=batches[0],
                                       fetch_list=fetch_list,
                                       scope=self._scope)]
        stacked = _stack_feeds(list(batches))
        outs = self._executor.run_steps(self._target, feed=stacked,
                                        fetch_list=fetch_list,
                                        scope=self._scope)
        return [[np.asarray(o)[i] for o in outs]
                for i in range(len(batches))]

    def _require_fresh_dir(self):
        """Refuse a pre-populated ckpt_dir: this run's step_0 baseline
        sorts OLDER than a previous run's step_48, so keep_last would
        prune it the moment it is written and the first restore would
        silently rewind into the previous run's stale trajectory."""
        if os.path.isdir(self._ckpt_dir):
            stale = sorted(d for d in os.listdir(self._ckpt_dir)
                           if d.startswith("step_")
                           and d.split("_", 1)[1].isdigit())
            if stale:
                raise ValueError(
                    "ckpt_dir %r already holds checkpoints (%s) — "
                    "ResilientTrainer.run starts a fresh trajectory at "
                    "step 0; give each run a clean directory"
                    % (self._ckpt_dir, ", ".join(stale)))

    def _resolved_fetch_list(self, fetch_list):
        fetch_list = fetch_list if fetch_list is not None \
            else self._fetch_list
        if not fetch_list:
            raise ValueError(
                "ResilientTrainer.run needs a fetch_list — an empty one "
                "would fall into Executor.run's eager path")
        return fetch_list

    def run(self, feeds=None, fetch_list=None, steps=None):
        """Run one step per feed dict in ``feeds``, recovering from
        transient faults. Returns the per-step fetch lists (replayed
        steps report their replayed — identical — values).

        ``feeds=None`` switches to the attached :class:`ShardedFeed`
        (``feed=`` at construction): up to ``steps`` dispatch windows
        pull their batches from the feed, the cursor rides every
        checkpoint, and a restore rewinds the data position with the
        params — exact-batch resume. The run ends early when the feed
        drains (``epochs=`` bound)."""
        if feeds is None:
            return self._run_feed(fetch_list, steps)
        feeds = list(feeds)
        n = len(feeds)
        fetch_list = self._resolved_fetch_list(fetch_list)
        if n == 0:
            return []
        all_fetches = [None] * n
        self._require_fresh_dir()
        # baseline snapshot: a fault before the first periodic save must
        # still have something valid to restore
        self._save(0)
        step, restarts = 0, 0
        while step < n:
            until_ckpt = self._checkpoint_every \
                - (step % self._checkpoint_every)
            w = min(self._steps_per_dispatch, n - step, until_ckpt)
            try:
                outs = self._dispatch(feeds, step, w, fetch_list)
                for i in range(w):
                    all_fetches[step + i] = outs[i]
                step += w
                at_boundary = step % self._checkpoint_every == 0 \
                    or step == n
                if at_boundary:
                    self._save(step)
                if watchdog.straggler_action_due() and not at_boundary:
                    # straggler MITIGATION: the detector saw a step past
                    # its critical threshold — snapshot NOW so the hang
                    # this straggler is about to become costs at most
                    # one step of replay
                    self._save(step)
                    record_event("straggler_ckpt", step=step)
            except Exception as e:
                step, restarts = self._recover(e, step, restarts)
        return all_fetches

    def _recover(self, e, step, restarts):
        """Shared single-host fault tail for run()/_run_feed(): classify,
        spend restart budget, back off, restore (params + any attached
        feed cursor). Returns the rewound (step, restarts); re-raises
        fatal errors and budget exhaustion."""
        if not self._policy.is_transient(e):
            record_event("fatal", step=step, error=type(e).__name__)
            raise e
        if isinstance(e, NumericFaultError) \
                and not isinstance(e, SkipBudgetExceededError):
            # numeric_policy="rewind": remember WHICH batch poisoned the
            # step so the post-restore replay runs without it — the
            # recovered trajectory equals the uninterrupted run minus
            # the poison batch (a deterministic NaN would otherwise
            # re-fire every replay until the budget converts it to a
            # hard failure)
            if e.batch_index is None:
                e.batch_index = step + int(e.window_offset or 0)
            if e.batch_index not in self._poison_batches:
                self._poison_batches.add(e.batch_index)
                record_event("poison_batch", batch=e.batch_index,
                             step=step, culprit=e.culprit)
        restarts += 1
        if restarts > self._max_restarts:
            record_event("giveup", step=step, restarts=restarts,
                         error=type(e).__name__)
            raise RestartBudgetExceededError(
                "restart budget (%d) exhausted at step %d; last "
                "error: %r" % (self._max_restarts, step, e))
        delay = self._policy.delay_s(restarts - 1)
        record_event("restart", step=step, restarts=restarts,
                     error=type(e).__name__, backoff_s=delay)
        _logger().warning(
            "step %d failed (%s: %s) — restart %d/%d after %.2fs",
            step, type(e).__name__, e, restarts,
            self._max_restarts, delay)
        self._policy.sleep(delay)
        return self._restore(), restarts

    def _run_feed(self, fetch_list, steps):
        """Feed-driven loop: windows pull from the attached ShardedFeed,
        ``step`` counts committed batches, every checkpoint carries the
        cursor, every restore rewinds it. Ends at ``steps`` batches or
        when the feed drains, whichever is first."""
        if self._feed is None:
            raise ValueError(
                "run(feeds=None) pulls from an attached ShardedFeed — "
                "pass feed= at construction (or pass feeds explicitly)")
        if steps is None or int(steps) < 1:
            raise ValueError("feed-driven run needs steps= >= 1 (an "
                             "upper bound; the feed draining ends the "
                             "run early)")
        n = int(steps)
        fetch_list = self._resolved_fetch_list(fetch_list)
        all_fetches = [None] * n
        self._require_fresh_dir()
        self._save(0)
        step, restarts = 0, 0
        while step < n:
            until_ckpt = self._checkpoint_every \
                - (step % self._checkpoint_every)
            w = min(self._steps_per_dispatch, n - step, until_ckpt)
            try:
                batches = self._feed.draw(w)
                outs = self._dispatch_window(batches, step, fetch_list)
                # the window ran: publish the cursor — a later fault
                # rewinds it to the last checkpoint with the params
                self._feed.commit()
                for i in range(len(outs)):
                    all_fetches[step + i] = outs[i]
                step += len(batches)
                drained = self._feed.drained
                at_boundary = step % self._checkpoint_every == 0 \
                    or step == n or drained
                if at_boundary:
                    self._save(step)
                    self._feed.record_metrics()
                elif watchdog.straggler_action_due():
                    self._save(step)
                    record_event("straggler_ckpt", step=step)
                if drained:
                    break
            except Exception as e:
                step, restarts = self._recover(e, step, restarts)
        return all_fetches[:step]


def __getattr__(name):
    # ElasticTrainer LIVES in coordination.py (it extends
    # PodResilientTrainer, and coordination imports this module at its
    # top, so a top-level import here would be circular) but is part of
    # the resilience API surface: resolve it lazily (PEP 562).
    if name == "ElasticTrainer":
        from .coordination import ElasticTrainer
        return ElasticTrainer
    raise AttributeError("module %r has no attribute %r"
                         % (__name__, name))
