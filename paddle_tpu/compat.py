"""paddle.compat parity (ref python/paddle/compat.py). The reference
papered over py2/py3; on py3-only these are mostly identities, kept so
ported call sites resolve."""
import math

__all__ = ["long_type", "to_text", "to_bytes", "round",
           "floor_division", "get_exception_message"]

long_type = int   # py2 long width handling (reference compat.py)


def _convert(obj, conv, inplace):
    if obj is None:
        return obj
    if isinstance(obj, dict):
        # keys AND values convert (reference to_text/to_bytes dict path)
        items = {_convert(k, conv, False): _convert(v, conv, False)
                 for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(items)
            return obj
        return items
    if isinstance(obj, (list, set)):
        if inplace:
            items = [_convert(i, conv, False) for i in obj]
            obj.clear()
            (obj.extend if isinstance(obj, list) else obj.update)(items)
            return obj
        return type(obj)(_convert(i, conv, False) for i in obj)
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    # non-bytes scalars pass through UNCHANGED (reference py3 behavior:
    # only bytes decode; numbers/bools keep their types)
    def conv(o):
        return o.decode(encoding) if isinstance(o, bytes) else o
    return _convert(obj, conv, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    def conv(o):
        return o if isinstance(o, bytes) else str(o).encode(encoding)
    return _convert(obj, conv, inplace)


def round(x, d=0):
    """Python-2-style half-away-from-zero rounding (the reference keeps
    this semantic difference from py3 banker's rounding)."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0:
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
