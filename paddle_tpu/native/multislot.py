"""MultiSlot text reader — the reference MultiSlotDataFeed's job.

Reference parity: paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed
::ParseOneInstance) + data_feed.proto slot config. One sample per text
line; per slot, in declared order: ``<count> v0 v1 ... v(count-1)``,
space-separated — exactly what ``incubate.data_generator`` emits and the
reference's ``pipe_command`` pipelines produce.

The hot parse runs in C++ (``dataplane.cc ms_parse_file``: whole file ->
packed binary blob in one call, GIL released by ctypes) with a pure-
Python fallback when the toolchain is unavailable. Each FILE is parsed
in memory (seekable regular files only — shard big corpora into many
files, as reference pipelines do); the dataset as a whole still streams
file by file.
"""
import struct

import numpy as np

from .build import load_dataplane


def _norm_dtype(d):
    d = str(d)
    if "float" in d:
        return "float32"
    if "int" in d:
        return "int64"
    raise ValueError("multislot slots are float or integer, got %r" % d)


class MultiSlotTextReader(object):
    """slots: [(name, dtype)] in the on-disk slot order. ``samples()``
    yields one {name: 1-D np.ndarray} dict per line."""

    def __init__(self, paths, slots):
        self._paths = list(paths)
        self._slots = [(name, _norm_dtype(dt)) for name, dt in slots]

    def samples(self):
        lib = load_dataplane()
        for path in self._paths:
            if lib is not None:
                for s in self._native(lib, path):
                    yield s
            else:
                for s in self._python(path):
                    yield s

    # -- native fast path ------------------------------------------------
    def _native(self, lib, path):
        import ctypes
        flags = (ctypes.c_ubyte * len(self._slots))(
            *[1 if dt == "float32" else 0 for _, dt in self._slots])
        out_len = ctypes.c_uint64()
        buf = lib.ms_parse_file(path.encode(), len(self._slots), flags,
                                ctypes.byref(out_len))
        if not buf:
            raise ValueError("multislot parse failed: %s"
                             % lib.ms_last_error().decode())
        try:
            data = ctypes.string_at(buf, out_len.value)
        finally:
            lib.dp_free(buf)
        n, = struct.unpack_from("=Q", data, 0)
        off = 8
        for _ in range(n):
            sample = {}
            for name, dt in self._slots:
                cnt, = struct.unpack_from("=I", data, off)
                off += 4
                if dt == "float32":
                    arr = np.frombuffer(data, np.float32, cnt, off)
                    off += 4 * cnt
                else:
                    arr = np.frombuffer(data, np.int64, cnt, off)
                    off += 8 * cnt
                sample[name] = arr
            yield sample

    # -- pure-python fallback (same contract, same errors) ---------------
    def _python(self, path):
        with open(path, "r") as f:
            for line_no, line in enumerate(f, 1):
                toks = line.split()
                if not toks:
                    continue
                sample, i = {}, 0
                for s, (name, dt) in enumerate(self._slots):
                    try:
                        cnt = int(toks[i])
                        if cnt < 0:
                            raise ValueError
                        i += 1
                        vals = toks[i:i + cnt]
                        if len(vals) != cnt:
                            raise ValueError
                        i += cnt
                    except (ValueError, IndexError):
                        raise ValueError(
                            "multislot parse failed: %s:%d: bad slot %d"
                            % (path, line_no, s))
                    sample[name] = np.asarray(
                        [float(v) if dt == "float32" else int(v)
                         for v in vals],
                        np.float32 if dt == "float32" else np.int64)
                if i != len(toks):
                    raise ValueError(
                        "multislot parse failed: %s:%d: trailing data "
                        "after the last slot" % (path, line_no))
                yield sample
