"""Build + load the native data plane via g++/ctypes (no pybind11 needed)."""
import ctypes
import hashlib
import os
import subprocess
import threading

_SRC = os.path.join(os.path.dirname(__file__), "dataplane.cc")
_lock = threading.Lock()
_lib = None
_build_error = None


def _cache_dir():
    d = os.environ.get("PADDLE_TPU_CACHE",
                       os.path.expanduser("~/.cache/paddle_tpu"))
    os.makedirs(d, exist_ok=True)
    return d


def _build():
    with open(_SRC, "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()[:16]
    so_path = os.path.join(_cache_dir(), "libdataplane_%s.so" % digest)
    if not os.path.exists(so_path):
        tmp = so_path + ".tmp.%d" % os.getpid()
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               _SRC, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, so_path)
    lib = ctypes.CDLL(so_path)
    lib.dp_reader_create.restype = ctypes.c_void_p
    lib.dp_reader_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_uint, ctypes.c_int]
    lib.dp_reader_next.restype = ctypes.c_int
    lib.dp_reader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
        ctypes.POINTER(ctypes.c_int64)]
    lib.dp_reader_destroy.argtypes = [ctypes.c_void_p]
    lib.dp_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.dp_writer_create.restype = ctypes.c_void_p
    lib.dp_writer_create.argtypes = [ctypes.c_char_p]
    lib.dp_writer_write.restype = ctypes.c_int
    lib.dp_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int64]
    lib.dp_writer_close.argtypes = [ctypes.c_void_p]
    lib.ms_parse_file.restype = ctypes.POINTER(ctypes.c_char)
    lib.ms_parse_file.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.POINTER(ctypes.c_ubyte),
        ctypes.POINTER(ctypes.c_uint64)]
    lib.ms_last_error.restype = ctypes.c_char_p
    return lib


def load_dataplane():
    """Return the loaded native library, or None if unavailable."""
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib
        try:
            _lib = _build()
        except Exception as e:  # toolchain missing etc. -> python fallback
            _build_error = e
        return _lib


def native_available():
    return load_dataplane() is not None


def build_error():
    return _build_error
