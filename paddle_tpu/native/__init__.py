"""Native (C++) runtime components.

Reference parity: the reference's C++ data plane (framework/data_feed.cc,
operators/reader/*). Compiled on first use with g++ (cached under
~/.cache/paddle_tpu); everything has a pure-Python fallback so the
framework works without a toolchain.
"""
from .build import load_dataplane, native_available
from .recordio import (RecordWriter, RecordReader, write_records,
                       NativeDataLoader)
