"""RecordIO-style binary sample files + native prefetching loader.

Reference parity: the reference's recordio reader (operators/reader/
create_recordio_file_reader) and MultiSlot data feed. Samples are pickled
tuples of numpy arrays; files are written/read through the C++ plane when
available (threaded, checksummed, shuffle pool), pure Python otherwise.
"""
import ctypes
import os
import pickle
import struct

import numpy as np

from .build import load_dataplane

_MAGIC = 0x70747263


def _fnv1a(data):
    h = 0xcbf29ce484222325
    for b in data:
        h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    return h


class RecordWriter(object):
    def __init__(self, path):
        self._lib = load_dataplane()
        self._path = path
        if self._lib is not None:
            self._w = self._lib.dp_writer_create(path.encode())
            if not self._w:
                raise IOError("cannot open %s" % path)
        else:
            self._f = open(path, "wb")

    def write(self, payload):
        if not isinstance(payload, (bytes, bytearray)):
            payload = pickle.dumps(payload, protocol=4)
        if self._lib is not None:
            ok = self._lib.dp_writer_write(self._w, bytes(payload),
                                           len(payload))
            if not ok:
                raise IOError("write failed")
        else:
            self._f.write(struct.pack("<IQQ", _MAGIC, len(payload),
                                      _fnv1a(payload)))
            self._f.write(payload)

    def write_sample(self, arrays):
        self.write(pickle.dumps(tuple(np.asarray(a) for a in arrays),
                                protocol=4))

    def close(self):
        if self._lib is not None:
            self._lib.dp_writer_close(self._w)
            self._w = None
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path, samples):
    with RecordWriter(path) as w:
        n = 0
        for s in samples:
            w.write_sample(s)
            n += 1
    return n


class RecordReader(object):
    """Iterates raw payload bytes from one or more record files.

    Native path: N reader threads + ring buffer + shuffle pool in C++.
    """

    def __init__(self, paths, buffer_records=256, shuffle_pool=0, seed=0,
                 num_threads=2):
        if isinstance(paths, str):
            paths = [paths]
        self._paths = list(paths)
        self._buffer = buffer_records
        self._pool = shuffle_pool
        self._seed = seed
        self._threads = num_threads
        self._lib = load_dataplane()

    def __iter__(self):
        if self._lib is not None:
            return self._iter_native()
        return self._iter_python()

    def _iter_native(self):
        lib = self._lib
        arr = (ctypes.c_char_p * len(self._paths))(
            *[p.encode() for p in self._paths])
        r = lib.dp_reader_create(arr, len(self._paths), self._buffer,
                                 self._pool, self._seed, self._threads)
        try:
            data = ctypes.POINTER(ctypes.c_char)()
            ln = ctypes.c_int64()
            while lib.dp_reader_next(r, ctypes.byref(data),
                                     ctypes.byref(ln)):
                payload = ctypes.string_at(data, ln.value)
                lib.dp_free(data)
                yield payload
        finally:
            lib.dp_reader_destroy(r)

    def _iter_python(self):
        import random
        rng = random.Random(self._seed)
        pool = []
        for path in self._paths:
            with open(path, "rb") as f:
                while True:
                    head = f.read(20)
                    if len(head) < 20:
                        break
                    magic, ln, hsh = struct.unpack("<IQQ", head)
                    if magic != _MAGIC:
                        break
                    payload = f.read(ln)
                    if len(payload) < ln or _fnv1a(payload) != hsh:
                        break
                    if self._pool > 0:
                        pool.append(payload)
                        if len(pool) >= self._pool:
                            i = rng.randrange(len(pool))
                            pool[i], pool[-1] = pool[-1], pool[i]
                            yield pool.pop()
                    else:
                        yield payload
        rng.shuffle(pool)
        for p in pool:
            yield p

    def samples(self):
        for payload in self:
            yield pickle.loads(payload)


class NativeDataLoader(object):
    """Batched loader over record files feeding Executor.run.

    feed_names: var names aligned with each sample tuple's arrays.
    """

    def __init__(self, paths, feed_names, batch_size, shuffle_pool=0,
                 seed=0, num_threads=2, drop_last=True):
        self._reader = RecordReader(paths, shuffle_pool=shuffle_pool,
                                    seed=seed, num_threads=num_threads)
        self._feed_names = list(feed_names)
        self._batch_size = batch_size
        self._drop_last = drop_last

    def __iter__(self):
        buf = []
        for sample in self._reader.samples():
            buf.append(sample)
            if len(buf) == self._batch_size:
                yield self._collate(buf)
                buf = []
        if buf and not self._drop_last:
            yield self._collate(buf)

    def _collate(self, samples):
        cols = list(zip(*samples))
        return {n: np.stack(c) for n, c in zip(self._feed_names, cols)}
