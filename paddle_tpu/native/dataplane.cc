// Native data plane: threaded record reader with ring buffer + shuffle pool.
//
// Reference parity: paddle/fluid/framework/data_feed.cc (MultiSlotDataFeed,
// channel-based readers) + operators/reader/buffered_reader.cc. The
// reference feeds CUDA streams; here the consumer is the Python host thread
// staging batches to TPU via jax.device_put, so the contract is:
// N file-reader threads -> bounded ring buffer (+ optional shuffle pool)
// -> single consumer pop.
//
// Record file format ("ptrec"):
//   magic  u32 = 0x70747263 ("ptrc")
//   len    u64 little-endian payload byte length
//   hash   u64 FNV-1a of payload (integrity check, no zlib dependency)
//   payload bytes
//
// Build: g++ -O2 -shared -fPIC -pthread (see build.py); exposed via ctypes.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x70747263u;

uint64_t fnv1a(const char* data, size_t n) {
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ull;
  }
  return h;
}

struct Record {
  char* data;
  int64_t len;
};

class RingBuffer {
 public:
  explicit RingBuffer(size_t capacity) : capacity_(capacity) {}

  void Push(Record r) {
    std::unique_lock<std::mutex> lk(mu_);
    not_full_.wait(lk, [&] { return q_.size() < capacity_ || closed_; });
    if (closed_) { std::free(r.data); return; }
    q_.push_back(r);
    not_empty_.notify_one();
  }

  bool Pop(Record* out) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [&] { return !q_.empty() || (done_ && active_ == 0) ||
                                     closed_; });
    if (closed_ || (q_.empty() && done_ && active_ == 0)) return false;
    *out = q_.front();
    q_.pop_front();
    not_full_.notify_one();
    return true;
  }

  void ProducerStart() {
    std::lock_guard<std::mutex> lk(mu_);
    ++active_;
  }

  void ProducerDone() {
    std::lock_guard<std::mutex> lk(mu_);
    if (--active_ == 0) { done_ = true; not_empty_.notify_all(); }
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    for (auto& r : q_) std::free(r.data);
    q_.clear();
    not_empty_.notify_all();
    not_full_.notify_all();
  }

 private:
  size_t capacity_;
  std::deque<Record> q_;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  int active_ = 0;
  bool done_ = false, closed_ = false;
};

class Reader {
 public:
  Reader(std::vector<std::string> paths, int buffer_records, int shuffle_pool,
         unsigned seed, int num_threads)
      : paths_(std::move(paths)),
        ring_(buffer_records > 0 ? buffer_records : 256),
        shuffle_pool_(shuffle_pool),
        rng_(seed) {
    int n = num_threads > 0 ? num_threads : 1;
    if (n > static_cast<int>(paths_.size())) n = paths_.size();
    if (n < 1) n = 1;
    for (int t = 0; t < n; ++t) {
      ring_.ProducerStart();
      threads_.emplace_back([this, t, n] { ReadFiles(t, n); });
    }
  }

  ~Reader() {
    ring_.Close();
    for (auto& th : threads_) th.join();
  }

  // Pops through the shuffle pool: fill pool to size, then emit a random
  // element per pop (reference InMemoryDataFeed local shuffle).
  bool Next(char** data, int64_t* len) {
    while (shuffle_pool_ > 0 &&
           static_cast<int>(pool_.size()) < shuffle_pool_) {
      Record r;
      if (!ring_.Pop(&r)) break;
      pool_.push_back(r);
    }
    if (!pool_.empty()) {
      std::uniform_int_distribution<size_t> d(0, pool_.size() - 1);
      size_t i = d(rng_);
      Record r = pool_[i];
      pool_[i] = pool_.back();
      pool_.pop_back();
      *data = r.data;
      *len = r.len;
      return true;
    }
    Record r;
    if (!ring_.Pop(&r)) return false;
    *data = r.data;
    *len = r.len;
    return true;
  }

 private:
  void ReadFiles(int tid, int stride) {
    for (size_t i = tid; i < paths_.size(); i += stride) {
      FILE* f = std::fopen(paths_[i].c_str(), "rb");
      if (!f) continue;
      while (true) {
        uint32_t magic;
        if (std::fread(&magic, 4, 1, f) != 1) break;
        if (magic != kMagic) break;  // corrupt/truncated tail
        uint64_t len, hash;
        if (std::fread(&len, 8, 1, f) != 1) break;
        if (std::fread(&hash, 8, 1, f) != 1) break;
        if (len > (1ull << 33)) break;
        char* buf = static_cast<char*>(std::malloc(len));
        if (!buf || std::fread(buf, 1, len, f) != len) {
          std::free(buf);
          break;
        }
        if (fnv1a(buf, len) != hash) {  // integrity failure: stop this file
          std::free(buf);
          break;
        }
        ring_.Push({buf, static_cast<int64_t>(len)});
      }
      std::fclose(f);
    }
    ring_.ProducerDone();
  }

  std::vector<std::string> paths_;
  RingBuffer ring_;
  int shuffle_pool_;
  std::vector<Record> pool_;
  std::mt19937 rng_;
  std::vector<std::thread> threads_;
};

struct Writer {
  FILE* f;
};

// ---------------------------------------------------------------------------
// MultiSlot text parser (reference framework/data_feed.cc MultiSlotDataFeed).
// One sample per line; per slot: "<count> v0 v1 ... v(count-1)", groups
// space-separated in slot order — the exact text format our
// incubate.data_generator emits and the reference's pipe_command feeds.
// Packed output layout (host-endian):
//   u64 n_samples
//   per sample, per slot: u32 count; count values (f32 if is_float else i64)
// ---------------------------------------------------------------------------

thread_local std::string g_ms_error;

// malloc-backed growable buffer: the parse result is handed to the caller
// as-is (ownership transfer, freed via dp_free) — no final copy.
struct Buf {
  char* p = nullptr;
  size_t len = 0, cap = 0;

  bool Append(const void* src, size_t n) {
    if (len + n > cap) {
      size_t want = cap ? cap * 2 : 4096;
      while (want < len + n) want *= 2;
      char* np = static_cast<char*>(std::realloc(p, want));
      if (!np) return false;
      p = np;
      cap = want;
    }
    std::memcpy(p + len, src, n);
    len += n;
    return true;
  }
};

template <typename T>
bool AppendRaw(Buf* out, T v) {
  return out->Append(&v, sizeof(T));
}

bool ParseLine(char* line, size_t line_no, const char* path, int n_slots,
               const unsigned char* is_float, Buf* out) {
  char* p = line;
  for (int s = 0; s < n_slots; ++s) {
    char* endp = nullptr;
    long long count = std::strtoll(p, &endp, 10);
    if (endp == p || count < 0 || count > (1ll << 31)) {
      g_ms_error = std::string(path) + ":" + std::to_string(line_no) +
                   ": bad slot count for slot " + std::to_string(s);
      return false;
    }
    p = endp;
    if (!AppendRaw(out, static_cast<uint32_t>(count))) {
      g_ms_error = "out of memory";
      return false;
    }
    for (long long i = 0; i < count; ++i) {
      if (is_float[s]) {
        float v = std::strtof(p, &endp);
        if (endp == p) {
          g_ms_error = std::string(path) + ":" + std::to_string(line_no) +
                       ": slot " + std::to_string(s) + " expects " +
                       std::to_string(count) + " float values";
          return false;
        }
        if (!AppendRaw(out, v)) {
          g_ms_error = "out of memory";
          return false;
        }
      } else {
        long long v = std::strtoll(p, &endp, 10);
        if (endp == p) {
          g_ms_error = std::string(path) + ":" + std::to_string(line_no) +
                       ": slot " + std::to_string(s) + " expects " +
                       std::to_string(count) + " int values";
          return false;
        }
        if (!AppendRaw(out, static_cast<int64_t>(v))) {
          g_ms_error = "out of memory";
          return false;
        }
      }
      p = endp;
    }
  }
  while (*p == ' ' || *p == '\t' || *p == '\r') ++p;
  if (*p != '\0') {
    g_ms_error = std::string(path) + ":" + std::to_string(line_no) +
                 ": trailing data after the last slot";
    return false;
  }
  return true;
}

}  // namespace

extern "C" {

void* dp_reader_create(const char** paths, int n_paths, int buffer_records,
                       int shuffle_pool, unsigned seed, int num_threads) {
  std::vector<std::string> p;
  for (int i = 0; i < n_paths; ++i) p.emplace_back(paths[i]);
  return new Reader(std::move(p), buffer_records, shuffle_pool, seed,
                    num_threads);
}

int dp_reader_next(void* r, char** data, int64_t* len) {
  return static_cast<Reader*>(r)->Next(data, len) ? 1 : 0;
}

void dp_reader_destroy(void* r) { delete static_cast<Reader*>(r); }

void dp_free(char* p) { std::free(p); }

void* dp_writer_create(const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  return w;
}

int dp_writer_write(void* vw, const char* data, int64_t len) {
  auto* w = static_cast<Writer*>(vw);
  uint64_t ulen = static_cast<uint64_t>(len);
  uint64_t hash = fnv1a(data, len);
  if (std::fwrite(&kMagic, 4, 1, w->f) != 1) return 0;
  if (std::fwrite(&ulen, 8, 1, w->f) != 1) return 0;
  if (std::fwrite(&hash, 8, 1, w->f) != 1) return 0;
  if (std::fwrite(data, 1, len, w->f) != static_cast<size_t>(len)) return 0;
  return 1;
}

void dp_writer_close(void* vw) {
  auto* w = static_cast<Writer*>(vw);
  std::fclose(w->f);
  delete w;
}

char* ms_parse_file(const char* path, int n_slots,
                    const unsigned char* is_float, uint64_t* out_len) {
  g_ms_error.clear();
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    g_ms_error = std::string("cannot open ") + path;
    return nullptr;
  }
  // seekable regular files only: pipes/FIFOs report ftell failure
  long sz = -1;
  if (std::fseek(f, 0, SEEK_END) == 0) sz = std::ftell(f);
  if (sz < 0 || std::fseek(f, 0, SEEK_SET) != 0) {
    std::fclose(f);
    g_ms_error = std::string(path) +
                 ": not a seekable regular file (pipe/FIFO?)";
    return nullptr;
  }
  std::vector<char> text(static_cast<size_t>(sz) + 1);
  if (sz > 0 && std::fread(text.data(), 1, sz, f) != static_cast<size_t>(sz)) {
    std::fclose(f);
    g_ms_error = std::string("short read on ") + path;
    return nullptr;
  }
  std::fclose(f);
  text[sz] = '\0';

  Buf out;
  uint64_t n_samples = 0;
  if (!out.Append(&n_samples, 8)) {  // patched at the end
    g_ms_error = "out of memory";
    return nullptr;
  }
  char* p = text.data();
  char* end = text.data() + sz;
  size_t line_no = 0;
  while (p < end) {
    char* nl = static_cast<char*>(std::memchr(p, '\n', end - p));
    char* line_end = nl ? nl : end;
    *line_end = '\0';
    ++line_no;
    bool blank = true;
    for (char* q = p; *q; ++q)
      if (*q != ' ' && *q != '\t' && *q != '\r') { blank = false; break; }
    if (!blank) {
      if (!ParseLine(p, line_no, path, n_slots, is_float, &out)) {
        std::free(out.p);
        return nullptr;
      }
      ++n_samples;
    }
    p = line_end + 1;
  }
  std::memcpy(out.p, &n_samples, 8);
  *out_len = out.len;
  return out.p;  // ownership transfers to the caller (dp_free)
}

const char* ms_last_error() { return g_ms_error.c_str(); }

}  // extern "C"
