"""Data readers.

Reference parity: python/paddle/reader/decorator.py + fluid/reader.py.
Python reader decorators here; the native C++ prefetch ring buffer lives in
paddle_tpu/native (SURVEY §2.9) with this module as its fallback.
"""
from .decorator import (batch, shuffle, buffered, chain, compose, firstn,
                        ComposeNotAligned,
                        map_readers, xmap_readers, cache, multiprocess_reader)
from .dataloader import DataLoader  # noqa
from .sharded_feed import (ShardedFeed, FeedStateError,  # noqa
                           FEED_STATE_VERSION)
