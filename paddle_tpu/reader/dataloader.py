"""DataLoader: batched host->device pipeline with async prefetch.

Reference parity: fluid.io.DataLoader / PyReader (python/paddle/fluid/
reader.py). TPU-native: batches are staged to device ahead of compute via a
background thread + jax.device_put, overlapping host preprocessing with TPU
step execution (JAX dispatch is async, so one-deep pipelining suffices).
"""
import queue
import threading

import numpy as np
import jax


class DataLoader(object):
    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False,
                       use_multiprocess=False):
        return _GeneratorLoader(feed_list, capacity, use_double_buffer)


class _GeneratorLoader(object):
    def __init__(self, feed_list, capacity, use_double_buffer):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._double_buffer = use_double_buffer
        self._batch_reader = None
        self._places = None

    def set_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        self._places = places
        return self

    def set_sample_list_generator(self, reader, places=None):
        names = [v.name for v in self._feed_list]

        def batched():
            for samples in reader():
                cols = list(zip(*samples))
                yield {n: np.stack([np.asarray(c) for c in col])
                       for n, col in zip(names, cols)}
        self._batch_reader = batched
        self._places = places
        return self

    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        from .decorator import batch as batch_dec
        return self.set_sample_list_generator(
            batch_dec(lambda: ([s] for s in reader()), batch_size,
                      drop_last=drop_last), places)

    def __call__(self):
        return iter(self)

    def __iter__(self):
        names = [v.name for v in self._feed_list]

        def to_feed(item):
            if isinstance(item, dict):
                return item
            if isinstance(item, (list, tuple)):
                return {n: np.asarray(v) for n, v in zip(names, item)}
            raise TypeError("batch generator must yield dict or tuple")

        if not self._double_buffer:
            for item in self._batch_reader():
                yield to_feed(item)
            return

        q = queue.Queue(maxsize=self._capacity)
        END = object()

        def producer():
            try:
                for item in self._batch_reader():
                    feed = to_feed(item)
                    # stage to device early: overlaps H2D with TPU compute
                    feed = {k: jax.device_put(np.asarray(v))
                            for k, v in feed.items()}
                    q.put(feed)
            finally:
                q.put(END)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is END:
                return
            yield item
