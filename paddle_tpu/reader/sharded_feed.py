"""Elastic, checkpointable sharded data feeds.

Reference parity: the reference fleet re-splits dataset file lists when
trainer membership changes (``distributed/fleet.py`` re-assigns filelists
per trainer; ``incubate/data_generator`` streams per-worker shards). This
module ports that pattern onto the TPU pod's coordinator so the *data*
side of recovery is as exact as the parameter side:

  * the sample space is partitioned into ``n_hosts`` **lanes** — lane
    ``l``'s share of epoch ``e`` is ``file_perm(seed, e)[l::n_hosts]``,
    a *splittable* derivation: any host can compute any lane's file and
    sample order from ``(seed, epoch, file_id)`` alone, so moving a lane
    between hosts moves only a tiny cursor, never data or RNG objects;
  * every cursor is ``{"epoch", "pos", "offset"}`` — epoch counter,
    index into the lane's file share, sample offset inside the (seeded,
    per-epoch shuffled) file — and the feed exposes the full pod map via
    :meth:`global_state` / :meth:`restore` so checkpoints carry the
    exact data position (``io.save_checkpoint(feed_state=...)``);
  * reads are transactional: :meth:`next_batch`/:meth:`draw` advance a
    *tentative* cursor, :meth:`commit` publishes it and
    :meth:`rollback`/:meth:`restore` discard it — the trainer commits
    only windows the whole pod agreed on, which is what makes the
    "every sample exactly once" census hold across faults;
  * :meth:`rebalance` re-maps lanes onto a new live-host set
    (``lane l -> live[l % len(live)]`` — deterministic, identity at full
    membership) so a dead host's unconsumed ranges flow to survivors and
    flow back on rejoin, all from the agreed cursor map.

The coordinator half lives in ``framework/coordination.py``: the window
status exchange carries each host's tentative cursor, so every host
always holds an agreed, committed view of every lane (``observe``).
"""
import copy
import random

import numpy as np

__all__ = ["ShardedFeed", "FeedStateError", "FEED_STATE_VERSION"]

FEED_STATE_VERSION = 1


class FeedStateError(ValueError):
    """A feed cursor is missing, malformed, from a newer library, or
    describes a different dataset/config than this feed was built with.
    Deliberately a ValueError: the resilience classifier treats it as
    FATAL — replaying from a wrong data position would silently corrupt
    the 'exactly once' guarantee, so it must never be retried away."""


def _default_collate(samples):
    """Stack a list of samples into one batch feed.

    dict samples -> {key: stacked array}; array-likes -> stacked array."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([np.asarray(s[k]) for s in samples])
                for k in first}
    return np.stack([np.asarray(s) for s in samples])


class ShardedFeed(object):
    """Fault-tolerant sharded feed over a list of sample files.

    ``files``: list of indexable sample containers (or zero-arg callables
    returning one — materialized lazily, cached). Samples are whatever
    the collate function understands; the default stacks dict-of-array
    samples into a feed dict. ``n_hosts`` is the FULL pod topology (the
    lane count — frozen for the feed's lifetime; membership changes
    re-map lanes, never re-cut them). ``epochs=None`` streams forever;
    an integer bounds the feed and :attr:`drained` turns True when every
    owned lane has served its last epoch.

    Determinism: with the same ``(files, n_hosts, seed)`` every
    permutation is derived from string-seeded ``random.Random`` (stable
    across processes and runs — no PYTHONHASHSEED exposure), so a
    restored cursor resumes the *exact* sample sequence, per lane,
    regardless of which host now owns the lane.
    """

    def __init__(self, files, n_hosts, host_id, seed=0, batch_size=None,
                 shuffle=True, epochs=None, collate=None,
                 weighted_rebalance=False):
        if n_hosts < 1:
            raise ValueError("n_hosts must be >= 1")
        if not 0 <= int(host_id) < int(n_hosts):
            raise ValueError("host_id %r out of range for %d hosts"
                             % (host_id, n_hosts))
        self._files = list(files)
        self.n_lanes = int(n_hosts)
        self.n_hosts = int(n_hosts)
        self._host_id = int(host_id)
        self.seed = int(seed)
        self.batch_size = None if batch_size is None else int(batch_size)
        self.shuffle = bool(shuffle)
        self.epochs = None if epochs is None else int(epochs)
        if self.epochs is not None and self.epochs < 1:
            raise ValueError("epochs must be >= 1 (or None for unbounded)")
        self._collate = collate or _default_collate
        if len(self._files) < self.n_lanes:
            raise ValueError(
                "ShardedFeed needs at least as many files as hosts "
                "(%d files < %d hosts): every lane must have a non-empty "
                "share each epoch" % (len(self._files), self.n_lanes))
        # empty files are rejected loudly: an all-empty lane share under
        # shuffle=False would otherwise spin _draw_from_lane through
        # epochs forever. Sequences are len()-probed here (free);
        # callables stay LAZY and are validated on first materialization
        for fid, f in enumerate(self._files):
            if not callable(f) and len(f) == 0:
                raise ValueError(
                    "ShardedFeed file %d is empty — every file must "
                    "hold at least one sample" % fid)
        self._materialized = {}
        self._lens = {}
        # caches for the splittable derivations (bounded: see _cache_put)
        self._file_perms = {}
        self._sample_perms = {}
        self._share_counts = {}
        # (lane, epoch) -> samples served in all epochs BEFORE epoch.
        # Unbounded on purpose: one int per lane-epoch, and keeping it
        # makes _consumed O(1) instead of O(epoch) per candidate lane
        # on every next_batch draw of a long run
        self._epoch_prefix = {}
        # weighted_rebalance=True: lanes ORPHANED by a membership change
        # (committed owner no longer live) are placed by the per-host
        # feed_stream_lag gauge — least-lagged survivors first — instead
        # of the round-robin formula; non-orphaned lanes keep following
        # round-robin, so full-membership identity (and the rejoin
        # hand-back) is unchanged. Falls back to round-robin whenever no
        # gauges are available. See rebalance() for the agreement caveat.
        self.weighted_rebalance = bool(weighted_rebalance)
        # committed view of EVERY lane (the agreed pod map) ...
        fresh = {"epoch": 0, "pos": 0, "offset": 0}
        self._known = {l: dict(fresh) for l in range(self.n_lanes)}
        self._live = list(range(self.n_lanes))
        # lane -> owning host (the round-robin identity at full
        # membership); kept explicit so weighted re-homing has a
        # committed owner to compare against
        self._owner = {l: l % self.n_lanes for l in range(self.n_lanes)}
        # ... and this host's owned slice: committed + tentative cursors
        self._own = self._owned_lanes(self._live)
        self._lanes = {l: dict(fresh) for l in self._own}
        self._pending = {l: dict(fresh) for l in self._own}
        self._last_epoch_event = None

    # -- dataset access ----------------------------------------------------
    def _file(self, fid):
        f = self._files[fid]
        if callable(f):
            if fid not in self._materialized:
                data = list(f())
                if not data:
                    raise ValueError(
                        "ShardedFeed file %d (callable) produced no "
                        "samples — every file must hold at least one"
                        % fid)
                self._materialized[fid] = data
            return self._materialized[fid]
        return f

    def _file_len(self, fid):
        if fid not in self._lens:
            self._lens[fid] = len(self._file(fid))
        return self._lens[fid]

    @property
    def samples_per_epoch(self):
        return sum(self._file_len(f) for f in range(len(self._files)))

    # -- splittable RNG derivations ----------------------------------------
    # string-seeded random.Random uses the hashlib path internally:
    # deterministic across processes, unaffected by PYTHONHASHSEED.
    def _rng(self, *key):
        return random.Random("paddle_tpu.feed:" +
                             ":".join(str(k) for k in key))

    def _file_perm(self, epoch):
        if epoch not in self._file_perms:
            perm = list(range(len(self._files)))
            if self.shuffle:
                self._rng(self.seed, epoch).shuffle(perm)
            self._cache_put(self._file_perms, epoch, perm)
        return self._file_perms[epoch]

    def _sample_perm(self, epoch, fid):
        key = (epoch, fid)
        if key not in self._sample_perms:
            perm = list(range(self._file_len(fid)))
            if self.shuffle:
                self._rng(self.seed, epoch, fid).shuffle(perm)
            self._cache_put(self._sample_perms, key, perm)
        return self._sample_perms[key]

    @staticmethod
    def _cache_put(cache, key, value, cap=256):
        if len(cache) >= cap:   # epochs advance monotonically: dropping
            cache.clear()       # everything is a rare, cheap full miss
        cache[key] = value

    def _share(self, lane, epoch):
        return self._file_perm(epoch)[lane::self.n_lanes]

    def _share_count(self, lane, epoch):
        key = (lane, epoch)
        if key not in self._share_counts:
            n = sum(self._file_len(f) for f in self._share(lane, epoch))
            self._cache_put(self._share_counts, key, n)
        return self._share_counts[key]

    # -- cursor math -------------------------------------------------------
    def _exhausted(self, cur):
        return self.epochs is not None and cur["epoch"] >= self.epochs

    def _consumed_epochs(self, lane, epoch):
        """Samples lane ``lane`` serves across epochs [0, epoch) —
        extends the nearest cached prefix, so the steady state (epoch
        advancing one at a time) costs O(1) per draw."""
        if (lane, epoch) not in self._epoch_prefix:
            e = epoch
            while e > 0 and (lane, e) not in self._epoch_prefix:
                e -= 1
            total = self._epoch_prefix.get((lane, e), 0)
            while e < epoch:
                total += self._share_count(lane, e)
                e += 1
            self._epoch_prefix[(lane, epoch)] = total
        return self._epoch_prefix[(lane, epoch)]

    def _consumed(self, lane, cur):
        """Total samples this lane has served up to ``cur``."""
        total = self._consumed_epochs(lane, cur["epoch"])
        if not self._exhausted(cur):
            share = self._share(lane, cur["epoch"])
            total += sum(self._file_len(f) for f in share[:cur["pos"]])
            total += cur["offset"]
        return total

    def _draw_from_lane(self, lane, cur, k):
        """Advance ``cur`` by up to ``k`` samples of lane ``lane``;
        returns the samples (shorter at the lane's final-epoch tail)."""
        out = []
        while len(out) < k and not self._exhausted(cur):
            share = self._share(lane, cur["epoch"])
            if cur["pos"] >= len(share):
                cur["epoch"] += 1
                cur["pos"] = 0
                cur["offset"] = 0
                continue
            fid = share[cur["pos"]]
            order = self._sample_perm(cur["epoch"], fid)
            if cur["offset"] >= len(order):
                cur["pos"] += 1
                cur["offset"] = 0
                continue
            out.append(self._file(fid)[order[cur["offset"]]])
            cur["offset"] += 1
        return out

    # -- reading -----------------------------------------------------------
    def next_batch(self):
        """Draw one batch from the least-consumed owned lane (tentative —
        call :meth:`commit` once the step using it is agreed). Batches
        never span lanes, so re-partitioning lanes re-partitions the
        batch stream exactly. Returns None when every owned lane has
        served its ``epochs`` quota (see :attr:`drained`)."""
        while True:
            cands = [l for l in self._own
                     if not self._exhausted(self._pending[l])]
            if not cands:
                return None
            # least-consumed first (ties -> lowest lane id): derived
            # purely from the cursors, so a restore replays the same
            # lane interleave with no extra state
            lane = min(cands, key=lambda l:
                       (self._consumed(l, self._pending[l]), l))
            samples = self._draw_from_lane(lane, self._pending[lane],
                                           self.batch_size or 1)
            if not samples:      # cursor sat exactly on the lane's end
                continue
            if self.batch_size is None:
                return samples[0]
            return self._collate(samples)

    def draw(self, k):
        """Up to ``k`` batches (one dispatch window's worth)."""
        out = []
        for _ in range(int(k)):
            b = self.next_batch()
            if b is None:
                break
            out.append(b)
        return out

    @property
    def drained(self):
        """True when every owned lane has served its ``epochs`` quota
        (tentative view — matches what the next draw would see)."""
        return all(self._exhausted(self._pending[l]) for l in self._own)

    def all_drained(self):
        """True when EVERY lane in the agreed pod map has served its
        ``epochs`` quota. Because the map is identical on every live
        host after a committed exchange, all hosts answer the same —
        the pod's drain consensus is computed, never voted."""
        return self.epochs is not None and all(
            self._exhausted(c) for c in self._known.values())

    @property
    def epoch(self):
        """Progress marker: the slowest owned lane's epoch (the quota
        when drained or nothing is owned)."""
        if not self._own:
            return 0 if self.epochs is None else self.epochs
        return min(self._pending[l]["epoch"] for l in self._own)

    # -- transactions ------------------------------------------------------
    def commit(self):
        """Publish the tentative cursors: the window they fed was agreed
        by the pod. Mirrors the owned slice into the pod map."""
        self._lanes = copy.deepcopy(self._pending)
        for l, cur in self._lanes.items():
            self._known[l] = dict(cur)

    def rollback(self):
        """Discard tentative reads (an un-agreed window re-draws them)."""
        self._pending = copy.deepcopy(self._lanes)

    # -- pod map exchange --------------------------------------------------
    def exchange_state(self):
        """This host's contribution to the window status exchange: its
        owned lanes' TENTATIVE cursors, the drained flag, and its
        committed stream lag. Peers observe the cursors only after the
        window commits; the lag rides along so every host can assemble
        the SAME ``{host: lag}`` snapshot from the frozen round
        verdicts — the agreed input ``weighted_rebalance`` needs on
        socket pods whose local event logs diverge."""
        return {"lanes": {str(l): dict(c)
                          for l, c in self._pending.items()},
                "drained": self.drained,
                "lag": self.stream_lag()}

    def observe(self, peer_state):
        """Fold a peer's (just-committed) exchange contribution into the
        pod map. Lanes this host currently owns are never overwritten —
        the local committed value is at least as fresh."""
        if not peer_state:
            return
        for l_str, cur in (peer_state.get("lanes") or {}).items():
            l = int(l_str)
            if l not in self._lanes and 0 <= l < self.n_lanes:
                self._known[l] = {"epoch": int(cur["epoch"]),
                                  "pos": int(cur["pos"]),
                                  "offset": int(cur["offset"])}

    def global_state(self):
        """The agreed, committed cursor of EVERY lane — what checkpoints
        persist (``io.save_checkpoint(feed_state=...)``) and what a
        rejoining host adopts. JSON-serializable and topology-free:
        restoring onto a different live set just re-maps lane ownership.
        """
        return {"version": FEED_STATE_VERSION, "seed": self.seed,
                "n_files": len(self._files), "n_lanes": self.n_lanes,
                "epochs": self.epochs,
                "lanes": {str(l): dict(c)
                          for l, c in self._known.items()},
                # the committed owner map rides the cursor (additive —
                # pre-existing cursors without it restore unchanged): a
                # weighted_rebalance joiner must run its orphan
                # detection against the POD's agreed map, not the stale
                # one it held when it was fenced
                "owners": {str(l): int(h)
                           for l, h in self._owner.items()}}

    # ``state()`` is the single-host-friendly alias
    state = global_state

    def restore(self, state, live=None, lags=None):
        """Adopt a :meth:`global_state` snapshot (from a checkpoint or a
        rejoin sync). ``live`` re-maps lane ownership at the same time —
        an 8-host cursor restored onto 6 live hosts resumes the exact
        global batch sequence with the 2 lost lanes re-homed.

        Weighted mode: the snapshot's ``owners`` map (when present) is
        adopted as the committed baseline BEFORE re-mapping, so this
        host's orphan detection agrees with the pod that produced the
        snapshot even if it missed intermediate re-balances while
        fenced; ``lags`` feeds the weighted placement exactly like
        :meth:`rebalance` (defaulting to the local event-log gauges —
        same agreement caveat)."""
        if not isinstance(state, dict) or "lanes" not in state:
            raise FeedStateError("feed cursor is missing or malformed: %r"
                                 % (state,))
        version = int(state.get("version", 0))
        if version > FEED_STATE_VERSION:
            raise FeedStateError(
                "feed cursor version %d is newer than this library's %d"
                % (version, FEED_STATE_VERSION))
        for key, mine in (("seed", self.seed),
                          ("n_files", len(self._files)),
                          ("n_lanes", self.n_lanes),
                          ("epochs", self.epochs)):
            theirs = state.get(key, mine)
            if theirs != mine:
                raise FeedStateError(
                    "feed cursor %s=%r does not match this feed's %r — "
                    "the cursor describes a different dataset or config"
                    % (key, theirs, mine))
        lanes = state["lanes"]
        missing = [l for l in range(self.n_lanes) if str(l) not in lanes]
        if missing:
            raise FeedStateError("feed cursor is missing lanes %s"
                                 % missing)
        self._known = {l: {"epoch": int(lanes[str(l)]["epoch"]),
                           "pos": int(lanes[str(l)]["pos"]),
                           "offset": int(lanes[str(l)]["offset"])}
                       for l in range(self.n_lanes)}
        owners = state.get("owners")
        if owners:
            self._owner = {int(l): int(h) for l, h in owners.items()}
        if lags is None and self.weighted_rebalance:
            lags = self._host_lags()
        self._remap(self._live if live is None else live, lags=lags)

    # -- membership --------------------------------------------------------
    def _lane_owners(self, live, lags=None):
        """lane -> owner over ``live``. Round-robin
        (``live[l % len(live)]``) by default; with weighted_rebalance
        and lag gauges, ORPHANED lanes (committed owner not in live) are
        instead distributed over hosts in ascending-lag order — the
        least-lagged survivors absorb the dead host's streams first.
        Deterministic for a given (live, lags): ties break on host id,
        orphans are assigned in lane order."""
        if not live:
            return {}
        rr = {l: live[l % len(live)] for l in range(self.n_lanes)}
        if not self.weighted_rebalance or not lags:
            return rr
        owners, orphans = {}, []
        for l in range(self.n_lanes):
            cur = self._owner.get(l)
            if cur is not None and cur not in live:
                orphans.append(l)
            else:
                owners[l] = rr[l]
        if orphans:
            order = sorted(live,
                           key=lambda h: (float(lags.get(h, 0.0)), h))
            for i, l in enumerate(orphans):
                owners[l] = order[i % len(order)]
        return owners

    def _owned_lanes(self, live):
        if self._host_id not in live:
            return []
        owners = self._lane_owners(live)
        return [l for l in range(self.n_lanes)
                if owners[l] == self._host_id]

    def _remap(self, live, lags=None):
        self._live = sorted(int(h) for h in live)
        self._owner = self._lane_owners(self._live, lags)
        self._own = [] if self._host_id not in self._live else \
            [l for l in range(self.n_lanes)
             if self._owner.get(l) == self._host_id]
        self._lanes = {l: dict(self._known[l]) for l in self._own}
        self._pending = copy.deepcopy(self._lanes)

    def _host_lags(self):
        """Last feed_stream_lag gauge per host from the resilience event
        log (the same aggregation resilience.metrics() exports), or None
        when no per-host gauges exist."""
        from ..framework.resilience import events
        lags = {}
        for e in events("feed_lag"):
            h = e.get("host")
            if h is not None:
                lags[int(h)] = float(e.get("lag", 0.0))
        return lags or None

    def rebalance(self, live, lags=None):
        """Deterministically re-map lanes onto the new live set. Default
        mapping is ``lane l -> live[l % len(live)]`` — the identity map
        at full membership, so a full-mesh rejoin restores the original
        split. With ``weighted_rebalance=True``, lanes orphaned by the
        change are instead placed by the per-host ``feed_stream_lag``
        gauge (``lags={host: lag}``, defaulting to the gauges in the
        local resilience event log), least-lagged survivors first;
        without any gauges the round-robin fallback applies unchanged.

        AGREEMENT (weighted mode): every live host must compute the
        SAME mapping, so the lag inputs must be agreed. The elastic
        trainers satisfy this automatically: :meth:`exchange_state`
        carries each host's ``stream_lag`` on the window status
        exchange, and ``ElasticTrainer`` passes the map assembled from
        the FROZEN round verdicts (``ElasticTrainer._agreed_lags``) to
        every re-balance AND to the consensus-rewind cursor restore —
        identical on every host, even on SocketCoordinator pods whose
        local event logs diverge.
        Only direct callers that skip the exchange still need to pass
        an agreed ``lags=`` themselves (the local-gauge default is
        safe only when the hosts share one event log).

        Resumes every lane from the agreed committed cursor, so the dead
        host's unconsumed ranges move wholesale to survivors — no sample
        lost, none duplicated. Also the grow half: the re-admitted host
        takes its lanes back at the admission barrier."""
        old = set(self._own)
        if lags is None and self.weighted_rebalance:
            lags = self._host_lags()
        self._remap(live, lags=lags)
        new = set(self._own)
        from ..framework.resilience import record_event
        record_event("feed_rebalance",
                     capacity="%d/%d" % (len(self._live), self.n_lanes),
                     gained=sorted(new - old), dropped=sorted(old - new),
                     weighted=bool(self.weighted_rebalance and lags))

    # -- observability -----------------------------------------------------
    def totals(self):
        """{host: committed samples served by its current lanes} from
        the agreed pod map — the per-host stream progress."""
        out = {}
        for l in range(self.n_lanes):
            owner = self._owner.get(l)
            if owner is None:
                continue
            out[owner] = out.get(owner, 0) \
                + self._consumed(l, self._known[l])
        return out

    def stream_lag(self):
        """Committed samples this host's streams trail the most-
        advanced host — the ``feed_stream_lag`` gauge value, computed
        straight from the agreed pod map (not the event log, so it is
        available before any ``record_metrics`` boundary)."""
        totals = self.totals()
        if not totals:
            return 0
        return int(max(totals.values())
                   - totals.get(self._host_id, 0))

    def record_metrics(self):
        """Emit the feed-plane gauges into the resilience event log:
        ``feed_epoch`` (slowest owned lane, on change) and ``feed_lag``
        (samples behind the most-advanced host). The trainer calls this
        at checkpoint boundaries, keeping the bounded log quiet."""
        from ..framework.resilience import record_event
        ep = self.epoch
        if ep != self._last_epoch_event:
            self._last_epoch_event = ep
            record_event("feed_epoch", epoch=int(ep))
        if self.totals():
            record_event("feed_lag", lag=self.stream_lag())
