"""Reader decorators.

Reference parity: python/paddle/reader/decorator.py — identical semantics
(a "reader" is a zero-arg callable returning an iterable of samples).
"""
import itertools
import random
import queue
import threading

import numpy as np


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def shuffle(reader, buf_size, seed=None):
    """Buffered shuffle. ``seed=None`` keeps the legacy behavior (the
    global ``random`` module — nondeterministic under concurrency).
    With a seed, each epoch (= each call of the returned reader) uses a
    fresh local ``random.Random`` derived from ``(seed, epoch)``:
    different epochs shuffle differently, but a rewind-and-replay that
    rebuilds the pipeline reproduces the exact sample order — the data
    half of the resilience stack's bitwise-identical replay. The string
    seeding goes through hashlib, so the order is stable across
    processes (no PYTHONHASHSEED exposure)."""
    epoch_box = [0]

    def shuffle_reader():
        if seed is None:
            rng = random
        else:
            e = epoch_box[0]
            epoch_box[0] = e + 1
            rng = random.Random("paddle_tpu.shuffle:%d:%d"
                                % (int(seed), e))
        buf = []
        for x in reader():
            buf.append(x)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            rng.shuffle(buf)
            for b in buf:
                yield b
    return shuffle_reader


def buffered(reader, size):
    """Background-thread prefetch buffer (the Python tier of the reference's
    double-buffered reader; the C++ ring buffer supersedes it when built)."""
    class _End(object):
        pass

    def buffered_reader():
        q = queue.Queue(maxsize=size)

        def worker():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)
        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _End:
                break
            yield item
    return buffered_reader


def chain(*readers):
    def chain_reader():
        for r in readers:
            for e in r():
                yield e
    return chain_reader


class ComposeNotAligned(ValueError):
    """Raised when composed readers end at different lengths
    (ref python/paddle/reader/decorator.py ComposeNotAligned)."""


def compose(*readers, **kwargs):
    check_alignment = kwargs.get("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def compose_reader():
        if not check_alignment:
            for outputs in zip(*[r() for r in readers]):
                yield sum([make_tuple(x) for x in outputs], ())
            return
        sentinel = object()
        for outputs in itertools.zip_longest(*[r() for r in readers],
                                             fillvalue=sentinel):
            if any(o is sentinel for o in outputs):
                raise ComposeNotAligned(
                    "outputs of readers are not aligned (different "
                    "lengths); pass check_alignment=False to truncate "
                    "to the shortest")
            yield sum([make_tuple(x) for x in outputs], ())
    return compose_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)
    return firstn_reader


def map_readers(func, *readers):
    def mapped():
        for vals in zip(*[r() for r in readers]):
            yield func(*vals)
    return mapped


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads."""
    def xmapped():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        END = object()

        def feeder():
            for i, s in enumerate(reader()):
                in_q.put((i, s))
            for _ in range(process_num):
                in_q.put(END)

        def worker():
            while True:
                item = in_q.get()
                if item is END:
                    out_q.put(END)
                    return
                i, s = item
                out_q.put((i, mapper(s)))

        threading.Thread(target=feeder, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=worker, daemon=True).start()
        finished = 0
        pending = {}
        next_i = 0
        while finished < process_num:
            item = out_q.get()
            if item is END:
                finished += 1
                continue
            i, s = item
            if not order:
                yield s
            else:
                pending[i] = s
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        for i in sorted(pending):
            yield pending[i]
    return xmapped


def cache(reader):
    all_data = []
    filled = []

    def cache_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)
    return cache_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Thread-based implementation (TPU hosts prefer threads: no CUDA ctx
    issues and the heavy lifting is numpy releasing the GIL)."""
    return chain(*readers)
