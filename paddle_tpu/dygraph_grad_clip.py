"""Module-path alias for fluid.dygraph_grad_clip (ref
python/paddle/fluid/dygraph_grad_clip.py)."""
from .dygraph.grad_clip import *  # noqa: F401,F403
from .dygraph import grad_clip as _gc

__all__ = list(getattr(_gc, "__all__", []))
