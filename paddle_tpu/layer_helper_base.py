"""Module-path alias for fluid.layer_helper_base (ref
python/paddle/fluid/layer_helper_base.py). The static/dygraph split the
reference needed collapses here: one LayerHelper serves both modes."""
from .layer_helper import LayerHelper as LayerHelperBase  # noqa: F401

__all__ = ["LayerHelperBase"]
