"""Model save/load + inference model freeze.

Reference parity: python/paddle/fluid/io.py (save_params, save_persistables,
load_params, load_persistables, save_inference_model, load_inference_model).
Format: <dir>/__model__.json (Program IR) + <dir>/params.npz (numpy archive)
replacing the reference's protobuf + per-var binary files. Atomic writes for
checkpoint/resume safety.
"""
import json
import os
import tempfile

import numpy as np

from .framework.program import Program, default_main_program, Parameter
from .framework.scope import global_scope

PARAMS_FILE = "params.npz"
MODEL_FILE = "__model__.json"


def _collect(program, scope, predicate):
    out = {}
    for var in program.list_vars():
        if not predicate(var):
            continue
        val = scope.find_var(var.name)
        if val is None:
            continue
        out[var.name] = np.asarray(val)
    return out


def _atomic_savez(dirname, filename, arrays):
    os.makedirs(dirname, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, os.path.join(dirname, filename))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_params(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    arrays = _collect(program, global_scope(),
                      lambda v: isinstance(v, Parameter))
    _atomic_savez(dirname, filename or PARAMS_FILE, arrays)


def save_persistables(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    arrays = _collect(program, global_scope(),
                      lambda v: v.persistable and not v.name.startswith("@"))
    _atomic_savez(dirname, filename or PARAMS_FILE, arrays)


def _load_arrays(dirname, filename):
    path = os.path.join(dirname, filename or PARAMS_FILE)
    with np.load(path, allow_pickle=False) as data:
        return {k: data[k] for k in data.files}


def load_params(executor, dirname, main_program=None, filename=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    arrays = _load_arrays(dirname, filename)
    scope = global_scope()
    wanted = {v.name for v in program.list_vars()
              if isinstance(v, Parameter)}
    for name in wanted:
        if name not in arrays:
            raise ValueError("parameter %r missing from checkpoint %s"
                             % (name, dirname))
        scope.set_var(name, jnp.asarray(arrays[name]))


def load_persistables(executor, dirname, main_program=None, filename=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    arrays = _load_arrays(dirname, filename)
    scope = global_scope()
    for v in program.list_vars():
        if v.persistable and v.name in arrays:
            scope.set_var(v.name, jnp.asarray(arrays[v.name]))


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """Freeze: clone for_test, prune to feeds/targets, save IR + params."""
    program = main_program or default_main_program()
    test_prog = program.clone(for_test=True)
    target_names = [v.name for v in target_vars]
    pruned = test_prog._prune(list(feeded_var_names), target_names)
    os.makedirs(dirname, exist_ok=True)
    meta = {"program": pruned.to_dict(),
            "feed_var_names": list(feeded_var_names),
            "fetch_var_names": target_names}
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    os.close(fd)
    with open(tmp, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(dirname, model_filename or MODEL_FILE))
    if not program_only:
        arrays = _collect(pruned, global_scope(), lambda v: v.persistable)
        _atomic_savez(dirname, params_filename or PARAMS_FILE, arrays)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import jax.numpy as jnp
    with open(os.path.join(dirname, model_filename or MODEL_FILE)) as f:
        meta = json.load(f)
    program = Program.from_dict(meta["program"])
    arrays = _load_arrays(dirname, params_filename)
    scope = global_scope()
    for name, arr in arrays.items():
        scope.set_var(name, jnp.asarray(arr))
    return program, meta["feed_var_names"], meta["fetch_var_names"]


# ---------------------------------------------------------------------------
# training checkpoint/resume (reference: fluid.io.save/load_checkpoint era
# APIs + incubate checkpoint): params + optimizer state + counters.
# ---------------------------------------------------------------------------

def save_checkpoint(executor, dirname, main_program=None, step=None,
                    keep_last=3):
    program = main_program or default_main_program()
    scope = global_scope()
    arrays = {}
    for name, val in scope.items():
        if val is None:
            continue
        arrays[name.replace("@", "__AT__")] = np.asarray(val)
    step_dir = "step_%d" % (step if step is not None else 0)
    _atomic_savez(os.path.join(dirname, step_dir), PARAMS_FILE, arrays)
    with open(os.path.join(dirname, "latest"), "w") as f:
        f.write(step_dir)
    # prune old checkpoints
    kids = sorted([d for d in os.listdir(dirname) if d.startswith("step_")],
                  key=lambda d: int(d.split("_")[1]))
    for d in kids[:-keep_last]:
        import shutil
        shutil.rmtree(os.path.join(dirname, d), ignore_errors=True)


def load_checkpoint(executor, dirname, main_program=None):
    import jax.numpy as jnp
    with open(os.path.join(dirname, "latest")) as f:
        step_dir = f.read().strip()
    arrays = _load_arrays(os.path.join(dirname, step_dir), PARAMS_FILE)
    scope = global_scope()
    for name, arr in arrays.items():
        scope.set_var(name.replace("__AT__", "@"), jnp.asarray(arr))
    return int(step_dir.split("_")[1])
