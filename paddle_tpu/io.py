"""Model save/load + inference model freeze.

Reference parity: python/paddle/fluid/io.py (save_params, save_persistables,
load_params, load_persistables, save_inference_model, load_inference_model).
Format: <dir>/__model__.json (Program IR) + <dir>/params.npz (numpy archive)
replacing the reference's protobuf + per-var binary files. Atomic writes for
checkpoint/resume safety.
"""
import json
import os
import tempfile
import threading

import numpy as np

from .framework.program import Program, default_main_program, Parameter
from .framework.scope import global_scope

PARAMS_FILE = "params.npz"
MODEL_FILE = "__model__.json"


def _collect(program, scope, predicate):
    out = {}
    for var in program.list_vars():
        if not predicate(var):
            continue
        val = scope.find_var(var.name)
        if val is None:
            continue
        out[var.name] = np.asarray(val)
    return out


def _fsync_dir(dirname):
    """Flush the DIRECTORY entry after an os.replace: the rename itself
    is atomic in the page cache, but a power cut can still lose it
    unless the directory metadata reaches disk too. Best-effort —
    platforms that cannot fsync a directory fd keep the rename-only
    guarantee."""
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_savez(dirname, filename, arrays, compressed=False):
    os.makedirs(dirname, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dirname, suffix=".tmp")
    os.close(fd)
    try:
        with open(tmp, "wb") as f:
            (np.savez_compressed if compressed else np.savez)(f, **arrays)
            # durability, not just atomicity: without the fsync a crash
            # after the rename can leave a VALID directory entry over
            # torn page-cache payloads — a checkpoint that lists as
            # complete but loads garbage
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(dirname, filename))
        _fsync_dir(dirname)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _atomic_write(path, text):
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    os.close(fd)
    try:
        with open(tmp, "w") as f:
            f.write(text)
            # the manifest IS the commit record: it must be durable
            # BEFORE the rename publishes it (see _atomic_savez)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def save_params(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    arrays = _collect(program, global_scope(),
                      lambda v: isinstance(v, Parameter))
    _atomic_savez(dirname, filename or PARAMS_FILE, arrays)


def save_persistables(executor, dirname, main_program=None, filename=None):
    program = main_program or default_main_program()
    arrays = _collect(program, global_scope(),
                      lambda v: v.persistable and not v.name.startswith("@"))
    _atomic_savez(dirname, filename or PARAMS_FILE, arrays)


def _load_arrays(dirname, filename):
    path = os.path.join(dirname, filename or PARAMS_FILE)
    with np.load(path, allow_pickle=False) as data:
        return {k: data[k] for k in data.files}


def load_params(executor, dirname, main_program=None, filename=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    arrays = _load_arrays(dirname, filename)
    scope = global_scope()
    wanted = {v.name for v in program.list_vars()
              if isinstance(v, Parameter)}
    for name in wanted:
        if name not in arrays:
            raise ValueError("parameter %r missing from checkpoint %s"
                             % (name, dirname))
        scope.set_var(name, jnp.asarray(arrays[name]))


def load_persistables(executor, dirname, main_program=None, filename=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    arrays = _load_arrays(dirname, filename)
    scope = global_scope()
    for v in program.list_vars():
        if v.persistable and v.name in arrays:
            scope.set_var(v.name, jnp.asarray(arrays[v.name]))


# Inference artifact format history (reference analogue: the predictor
# config/version machinery in
# paddle/fluid/inference/api/analysis_predictor.h:47):
#   v1 (implicit — no "format_version" key): program + feeds/fetches only.
#   v2: + "format_version", + "param_manifest" {name: {shape, dtype}}
#       validated against params.npz at load with named errors.
INFERENCE_FORMAT_VERSION = 2


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False, format="default",
                         batch_sizes=(1, 8, 32), example_feed=None,
                         feed_batch_factors=None, weight_compress=None):
    """Freeze: clone for_test, prune to feeds/targets, save IR + params.

    format="stablehlo" additionally writes a deployable serving artifact
    under dirname/serving/ — serialized jax.export blobs plus StableHLO
    MLIR text a C++ PjRt service can compile without Python (the
    reference's C++ PaddlePredictor capability, paddle_api.h:148); load
    with paddle_tpu.serving.load_serving_artifact. batch_sizes are the
    exported batch buckets (XLA artifacts are static-shape).
    weight_compress="q8" ships the serving artifact's weights as
    block-quantized int8 beside the export instead of baked fp32
    constants inside it — see serving.export_serving_artifact."""
    if format not in ("default", "stablehlo"):
        # validate BEFORE writing anything: a typo'd format must not
        # leave a half-configured artifact directory behind
        raise ValueError("save_inference_model format must be 'default' "
                         "or 'stablehlo', got %r" % (format,))
    if format == "stablehlo" and not batch_sizes:
        raise ValueError("format='stablehlo' needs at least one "
                         "batch_sizes entry")
    program = main_program or default_main_program()
    test_prog = program.clone(for_test=True)
    target_names = [v.name for v in target_vars]
    pruned = test_prog._prune(list(feeded_var_names), target_names)
    os.makedirs(dirname, exist_ok=True)
    arrays = None
    manifest = {}
    if not program_only:
        arrays = _collect(pruned, global_scope(), lambda v: v.persistable)
        manifest = {name: {"shape": list(arr.shape),
                           "dtype": arr.dtype.name}
                    for name, arr in arrays.items()}
    meta = {"format_version": INFERENCE_FORMAT_VERSION,
            "program": pruned.to_dict(),
            "feed_var_names": list(feeded_var_names),
            "fetch_var_names": target_names,
            "param_manifest": manifest}
    _atomic_write(os.path.join(dirname, model_filename or MODEL_FILE),
                  json.dumps(meta))
    if arrays is not None:
        _atomic_savez(dirname, params_filename or PARAMS_FILE, arrays)
    if format == "stablehlo":
        from .serving import export_serving_artifact
        export_serving_artifact(dirname, feeded_var_names, target_vars,
                                executor, batch_sizes=batch_sizes,
                                pruned_program=pruned,
                                example_feed=example_feed,
                                feed_batch_factors=feed_batch_factors,
                                weight_compress=weight_compress)
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    import jax.numpy as jnp
    model_path = os.path.join(dirname, model_filename or MODEL_FILE)
    if not os.path.exists(model_path):
        raise ValueError("inference model file %r does not exist"
                         % model_path)
    with open(model_path) as f:
        meta = json.load(f)
    version = meta.get("format_version", 1)   # v1 artifacts predate the key
    if version > INFERENCE_FORMAT_VERSION:
        raise ValueError(
            "inference model %s has format_version %d, newer than this "
            "library's %d — upgrade paddle_tpu to load it"
            % (dirname, version, INFERENCE_FORMAT_VERSION))
    program = Program.from_dict(meta["program"])
    arrays = _load_arrays(dirname, params_filename)
    manifest = meta.get("param_manifest") or {}
    if manifest:
        missing = sorted(set(manifest) - set(arrays))
        if missing:
            raise ValueError(
                "inference model %s: params file is missing variables %s "
                "declared in the manifest" % (dirname, missing))
        for name, spec in manifest.items():
            arr = arrays[name]
            if list(arr.shape) != list(spec["shape"]):
                raise ValueError(
                    "inference model %s: variable %r has shape %s on disk "
                    "but the manifest declares %s"
                    % (dirname, name, list(arr.shape), spec["shape"]))
            if arr.dtype.name != spec["dtype"]:
                raise ValueError(
                    "inference model %s: variable %r has dtype %s on disk "
                    "but the manifest declares %s"
                    % (dirname, name, arr.dtype.name, spec["dtype"]))
    scope = global_scope()
    for name, arr in arrays.items():
        scope.set_var(name, jnp.asarray(arr))
    return program, meta["feed_var_names"], meta["fetch_var_names"]


# ---------------------------------------------------------------------------
# training checkpoint/resume (reference: fluid.io.save/load_checkpoint era
# APIs + incubate checkpoint): params + optimizer state + counters.
#
# Sharded, multi-host-safe format (reference analogue:
# fluid.io._save_distributed_persistables, python/paddle/fluid/io.py:347 —
# each pserver saves the vars IT owns; here each jax process saves the
# array shards IT holds):
#   <dir>/step_N/shards_p{process}.npz   per-process shard payloads
#   <dir>/step_N/manifest.json           written LAST by process 0 — the
#                                        commit point: format version, step,
#                                        per-var {shape, dtype, shards:
#                                        [{offsets, file, key}]}
# Restore stitches by offsets, so the saving and restoring meshes may have
# DIFFERENT topologies (dp2xmp2 -> dp4xmp2 resharding is just slicing).
# ---------------------------------------------------------------------------

# v1: plain npz shard payloads. v2: adds compressed payloads — "zlib"
# (np.savez_compressed; npz layout unchanged, np.load reads it
# transparently, so v2-zlib dirs are still WRITTEN as version 1) and
# "q8" (block-quantized int8 members + ##q8* companions — LOSSY, so q8
# dirs are stamped version 2: an older library refuses them with
# CheckpointFormatError instead of restoring int8 garbage).
CKPT_FORMAT_VERSION = 2

# block-quantized payload companions (member-name suffixes next to the
# main shard key; scrub's needed-key check only ever looks at main keys,
# so verdicts are identical with or without them)
_Q8_SCALE = "##q8s"
_Q8_SHAPE = "##q8n"
_Q8_DTYPE = "##q8t"


def _encode_payload(own, compress, block_size=256):
    """Encode a {key: array} shard payload for ``compress`` mode. Only
    "q8" transforms anything: float32/float64 arrays of at least one
    block become int8 blocks + fp32 scales + shape/dtype companions;
    everything else (ints, tiny floats, exotic dtypes) stays raw so it
    round-trips exactly."""
    if compress != "q8":
        return own
    from .ops import quant_ops
    out = {}
    for key, arr in own.items():
        if arr.dtype in (np.float32, np.float64) \
                and arr.size >= block_size:
            q, scale = quant_ops.np_block_quantize(arr, block_size)
            out[key] = q
            out[key + _Q8_SCALE] = scale
            out[key + _Q8_SHAPE] = np.asarray(arr.shape, np.int64)
            out[key + _Q8_DTYPE] = np.asarray(arr.dtype.str)
        else:
            out[key] = arr
    return out


def _decode_member(z, key):
    """Read one npz member, transparently dequantizing a q8-encoded one
    (its ##q8s companion is the marker). Plain members — every pre-v2
    checkpoint — pass straight through."""
    arr = z[key]
    if key + _Q8_SCALE in z.files:
        from .ops import quant_ops
        return quant_ops.np_block_dequantize(
            arr, z[key + _Q8_SCALE],
            tuple(int(d) for d in z[key + _Q8_SHAPE]),
            np.dtype(str(z[key + _Q8_DTYPE])))
    return arr


def encode_state_blob(arrays, step, compress="zlib", feed_state=None):
    """One JSON-safe blob of a ``{name: array}`` state snapshot, using
    the CHECKPOINT payload codec (:func:`_encode_payload` /
    :func:`_decode_member`, same npz member layout and q8 companions) —
    the buddy-checkpoint tier and any future in-memory state movement
    share the disk format's exact encode/decode instead of growing a
    second one. ``compress`` follows save_checkpoint: None (plain npz),
    "zlib" (LOSSLESS deflate — the bitwise-parity default), "q8"
    (block-quantized, LOSSY).

    Returns ``(blob, raw_bytes, wire_bytes)`` where ``blob`` is a JSON-
    serializable dict (the npz bytes ride base64) and the byte pair is
    the record_bytes raw-vs-wire accounting."""
    import base64
    from io import BytesIO
    if compress not in (None, "zlib", "q8"):
        raise ValueError("encode_state_blob compress must be None, "
                         "'zlib' or 'q8', got %r" % (compress,))
    own, names = {}, {}
    for name, arr in sorted(arrays.items()):
        a = np.asarray(arr)
        safe = name.replace("/", "#SL#")
        names[safe] = name
        own[safe] = a
    raw = sum(int(a.nbytes) for a in own.values())
    buf = BytesIO()
    (np.savez_compressed if compress is not None else np.savez)(
        buf, **_encode_payload(own, compress))
    data = buf.getvalue()
    blob = {"v": 1, "step": int(step),
            "names": names,
            "npz": base64.b64encode(data).decode("ascii")}
    if compress is not None:
        blob["compress"] = compress
    if feed_state is not None:
        blob["feed_state"] = feed_state
    return blob, raw, len(data)


def decode_state_blob(blob):
    """Inverse of :func:`encode_state_blob`: returns
    ``(arrays, step, feed_state)`` with q8 members transparently
    dequantized. Raises on a torn/garbage blob (ValueError/KeyError/
    zipfile errors) — callers treat any failure as ``snapshot_torn``
    and fall back to the disk path."""
    import base64
    from io import BytesIO
    data = base64.b64decode(blob["npz"])
    names = blob.get("names", {})
    out = {}
    with np.load(BytesIO(data), allow_pickle=False) as z:
        for key in z.files:
            if key.endswith((_Q8_SCALE, _Q8_SHAPE, _Q8_DTYPE)):
                continue
            out[names.get(key, key)] = _decode_member(z, key)
    return out, int(blob["step"]), blob.get("feed_state")


def leaf_digest(arr):
    """Content digest of ONE state leaf: sha256 over dtype + shape +
    raw bytes (C-order). Drives the buddy delta-snapshot skip test — a
    leaf whose digest is unchanged since the last acked generation is
    not re-sent — so it must be bitwise-exact, never approximate."""
    import hashlib
    a = np.ascontiguousarray(np.asarray(arr))
    h = hashlib.sha256()
    h.update(str(a.dtype.str).encode("ascii"))
    h.update(repr(tuple(a.shape)).encode("ascii"))
    h.update(a.tobytes())
    return h.hexdigest()


def leaf_digests(arrays):
    """``{name: leaf_digest(arr)}`` for a state mapping."""
    return {name: leaf_digest(arr) for name, arr in arrays.items()}


def state_digest(arrays):
    """Order-independent digest of a WHOLE ``{name: array}`` state:
    sha256 over the sorted (name, leaf_digest) pairs. The buddy tier
    publishes this to the coordinator metadata table and every restore
    verifies the reconstructed state against it, so a torn p2p stream
    or a corrupt delta chain can never be silently adopted."""
    import hashlib
    h = hashlib.sha256()
    for name in sorted(arrays):
        h.update(str(name).encode("utf-8"))
        h.update(b"\x00")
        h.update(leaf_digest(arrays[name]).encode("ascii"))
    return h.hexdigest()


class CheckpointFormatError(RuntimeError):
    """The checkpoint on disk is VALID but written by a newer library.
    Deliberately not an OSError/ValueError: load_checkpoint's corruption
    fallback must never quarantine (rename) a healthy too-new
    checkpoint — upgrade the library instead."""
MANIFEST_FILE = "manifest.json"


def _offset_list(idx, shape):
    """Normalize a devices_indices_map entry to [[start, stop], ...]."""
    out = []
    for s, dim in zip(idx, shape):
        start = 0 if s.start is None else int(s.start)
        stop = dim if s.stop is None else int(s.stop)
        out.append([start, stop])
    return out


def _shard_plan(val):
    """Distinct shard extents of a jax.Array -> owning device.

    Replicas (several devices holding the same index) dedupe to the
    lowest device id, so every byte is written exactly once across all
    processes."""
    shape = val.shape
    plan = {}
    for dev, idx in val.sharding.devices_indices_map(shape).items():
        key = tuple(tuple(p) for p in _offset_list(idx, shape))
        if key not in plan or dev.id < plan[key].id:
            plan[key] = dev
    return plan


class AsyncCheckpoint(object):
    """Handle for a save_checkpoint(..., blocking=False) in flight.
    result() joins the writer thread and re-raises any commit failure."""

    def __init__(self, thread, box):
        self._thread = thread
        self._box = box

    def done(self):
        return not self._thread.is_alive()

    def result(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError("checkpoint commit still in flight")
        if self._box.get("error") is not None:
            raise self._box["error"]


_pending_save = [None]   # at most one async commit in flight per process
_atexit_registered = False


def wait_for_pending_saves():
    """Block until a previous blocking=False checkpoint has committed."""
    h = _pending_save[0]
    if h is not None:
        # clear the slot FIRST: a failed commit must raise once, not
        # poison every later save/load with the same stale error
        _pending_save[0] = None
        h.result()


def save_checkpoint(executor, dirname, main_program=None, step=None,
                    keep_last=3, blocking=True, scope=None,
                    feed_state=None, compress=None):
    """Sharded checkpoint of the whole training scope.

    compress: payload compression for the shard npz files.

      None    (default) plain npz — byte-identical to the historical
              format.
      "zlib"  LOSSLESS deflate (np.savez_compressed). Same members, same
              manifest, still written as format_version 1 — any library
              version reads it transparently. The safe default for sync/
              state-ship checkpoints: restores stay bitwise.
      "q8"    block-quantized int8 payloads + per-block fp32 scales
              (ops/quant_ops codec) for float32/float64 arrays of at
              least one block; LOSSY (per-block abs-max error envelope).
              Stamped format_version 2 so an older library refuses it
              instead of restoring int8 garbage; this library's
              load_checkpoint dequantizes transparently. scrub verdicts
              are unchanged either way (companions are extra members the
              needed-key check never looks at).

    Every commit records the raw-vs-wire byte pair under the ``ckpt``
    channel of ``resilience.bytes_totals()`` (raw = array bytes as
    collected, wire = npz bytes on disk), so compression ratios are
    assertable from ``resilience.metrics()``.

    feed_state: optional JSON-serializable dataset cursor (e.g.
    ``reader.ShardedFeed.global_state()``) persisted in the manifest's
    ``feed_state`` field, next to the params. It carries its own
    ``version`` key (reader.FEED_STATE_VERSION); scrub classification is
    untouched by its presence or absence — the field rides the manifest
    JSON that scrub already reads, and no payload bytes are added.

    Multi-host semantics: every process calls this with the same args;
    each writes only its addressable (deduped) shards, all processes
    barrier, then process 0 alone commits manifest.json + "latest" and
    prunes old step dirs.  A crash before the manifest leaves the
    previous checkpoint as "latest" — restores never see a torn save.

    scope: the Scope to snapshot (default the global scope). An explicit
    scope is what lets N simulated pod hosts in ONE process (coordination
    .PodResilientTrainer) checkpoint disjoint state.

    blocking=False (single-host only): device->host materialization
    still happens synchronously — the step's donation invalidates device
    buffers, so the bytes must leave the chip before returning — but the
    file writing + manifest commit move to a background thread and an
    AsyncCheckpoint handle is returned. Training resumes immediately;
    the next save (or load, or wait_for_pending_saves) joins the
    previous commit first.
    """
    import jax
    if compress not in (None, "zlib", "q8"):
        raise ValueError("save_checkpoint compress must be None, 'zlib' "
                         "or 'q8', got %r" % (compress,))
    scope = scope if scope is not None else global_scope()
    pid = jax.process_index()
    step_no = int(step if step is not None else 0)
    step_dir = "step_%d" % step_no
    full_dir = os.path.join(dirname, step_dir)
    wait_for_pending_saves()

    own, manifest_vars = {}, {}
    for name, val in sorted(scope.items()):
        if val is None:
            continue
        # shard keys are derived from the VAR NAME (sanitized for npz/zip
        # member names), never a global counter: if the scopes of two
        # processes ever diverge, the manifest's key is absent from the
        # divergent process's npz and the load fails HARD (KeyError)
        # instead of silently restoring the wrong tensor.
        safe = name.replace("/", "#SL#")
        if isinstance(val, jax.Array) and not val.is_fully_replicated:
            shape, dtype = val.shape, np.dtype(val.dtype)
            local = {tuple(tuple(p) for p in _offset_list(s.index, shape)):
                     s for s in val.addressable_shards}
            shards = []
            for j, (offs, dev) in enumerate(
                    sorted(_shard_plan(val).items(), key=lambda kv: kv[0])):
                key = "%s##%d" % (safe, j)
                shards.append({"offsets": [list(p) for p in offs],
                               "file": "shards_p%d.npz" % dev.process_index,
                               "key": key})
                if dev.process_index == pid:
                    own[key] = np.asarray(local[offs].data)
            manifest_vars[name] = {"shape": list(shape),
                                   "dtype": dtype.name, "shards": shards}
        else:
            # replicated/host value: only process 0 transfers + writes it;
            # other processes record metadata without touching the bytes
            shape = tuple(getattr(val, "shape", ()) or ())
            dtype = np.dtype(getattr(val, "dtype", None) or
                             np.asarray(val).dtype)
            key = "%s##full" % safe
            if pid == 0:
                arr = np.asarray(val)
                shape, dtype = arr.shape, arr.dtype
                own[key] = arr
            manifest_vars[name] = {
                "shape": list(shape), "dtype": dtype.name,
                "shards": [{"offsets": [[0, d] for d in shape],
                            "file": "shards_p0.npz", "key": key}]}

    multihost = jax.process_count() > 1
    n_proc = jax.process_count()

    def commit():
        from .framework import faultinject
        raw_bytes = sum(int(a.nbytes) for a in own.values())
        shard_file = "shards_p%d.npz" % pid
        faultinject.hit("io.member_write", host=pid)
        _atomic_savez(full_dir, shard_file,
                      _encode_payload(own, compress),
                      compressed=compress is not None)
        from .framework import resilience
        try:
            resilience.record_bytes(
                "ckpt", raw_bytes,
                os.path.getsize(os.path.join(full_dir, shard_file)))
        except OSError:  # pragma: no cover - racing cleanup
            pass
        # chaos injection point: an I/O fault HERE (shards written,
        # manifest not) models a mid-commit crash — the step dir is torn
        # and load_checkpoint must quarantine it, never restore from it
        resilience.fire("ckpt_write", what=step_dir)
        if multihost:  # pragma: no cover - needs real multihost
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("ckpt_shards_%s" % step_dir)
        if pid == 0:
            # only the LOSSY q8 layout needs the version fence; zlib npz
            # is transparently readable by every library version
            version = 2 if compress == "q8" else 1
            manifest = {"format_version": version,
                        "step": step_no, "process_count": n_proc,
                        "vars": manifest_vars}
            if compress is not None:
                manifest["compress"] = compress
            if feed_state is not None:
                manifest["feed_state"] = feed_state
            # shards are on disk but the manifest — the commit record —
            # is not: a fault HERE must leave a torn step dir that
            # load_checkpoint quarantines, never a half-trusted one
            faultinject.hit("io.manifest_write", host=pid)
            _atomic_write(os.path.join(full_dir, MANIFEST_FILE),
                          json.dumps(manifest))
            _atomic_write(os.path.join(dirname, "latest"), step_dir)
            _prune_step_dirs(dirname, keep_last)
        if multihost:  # pragma: no cover - needs real multihost
            # hold every process until the manifest commit is durable — a
            # worker returning (and its orchestrator tearing the job
            # down) while process 0 is still writing must not lose the
            # checkpoint
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("ckpt_commit_%s" % step_dir)

    import threading

    if blocking or multihost:
        # multihost stays synchronous: barriers from a daemon thread
        # would deadlock against the main thread's collectives. Return
        # an already-completed handle when the caller asked for async so
        # `h.result()` code works unchanged on both topologies.
        commit()
        if blocking:
            return None
        done = threading.Thread(target=lambda: None)
        done.start()
        done.join()
        return AsyncCheckpoint(done, {"error": None})

    box = {"error": None}

    def runner():
        try:
            commit()
        except BaseException as e:  # pragma: no cover - disk dependent
            box["error"] = e

    # joined via atexit (orbax-style): the run's LAST async checkpoint
    # must not be killed mid-write at interpreter shutdown
    global _atexit_registered
    if not _atexit_registered:
        import atexit
        atexit.register(wait_for_pending_saves)
        _atexit_registered = True
    th = threading.Thread(target=runner, name="ckpt-commit-%d" % step_no,
                          daemon=True)
    th.start()
    handle = AsyncCheckpoint(th, box)
    _pending_save[0] = handle
    return handle


def _prune_step_dirs(dirname, keep_last):
    """Scrub-aware retention: keep the newest ``keep_last`` scrub-VALID
    step dirs; everything older than the keep_last-th valid one is
    pruned.

    Torn/incomplete dirs (a burst of mid-commit crashes) do NOT consume
    retention slots — under the old count-all-dirs rule a burst of torn
    saves could evict every restorable checkpoint while keeping only
    wreckage. Invalid dirs NEWER than the retention cutoff are kept (an
    in-flight async commit looks exactly like a torn save until its
    manifest lands — deleting it would corrupt a healthy checkpoint);
    once they age past the cutoff they are pruned with everything else.
    Quarantined ``step_N.corrupt`` dirs never match the pattern and stay
    for forensics, as before. Validity comes from _classify_step_dir —
    the same classifier scrub and load-quarantine use — and only the
    newest ~keep_last dirs are classified (manifest JSON + npz member
    lists, never payloads), so the cost per save stays O(keep_last).
    keep_last <= 0 prunes nothing (the historical behavior — it must
    never delete the checkpoint that was just committed).

    Serialized against scrub_checkpoint by _RETENTION_LOCK: an async
    commit's GC racing a restore election's scrub could otherwise
    collect the very step the scrub just called valid (the buddy-tier
    disk fallback elects from that report) — classification and
    deletion must observe each other atomically."""
    import shutil
    if keep_last <= 0:
        return
    with _RETENTION_LOCK:
        kids = sorted([d for d in os.listdir(dirname)
                       if d.startswith("step_")
                       and d.split("_", 1)[1].isdigit()],
                      key=lambda d: int(d.split("_")[1]), reverse=True)
        seen_valid = 0
        for d in kids:
            if seen_valid >= keep_last:
                shutil.rmtree(os.path.join(dirname, d),
                              ignore_errors=True)
                continue
            status, _reason = _classify_step_dir(dirname, d)
            if status == "valid":
                seen_valid += 1


# One lock serializes retention GC (_prune_step_dirs, possibly on an
# async-commit thread) against restore-side scrub classification
# (scrub_checkpoint): a GC deleting dirs mid-scrub would let the scrub
# report a valid step that no longer exists by the time the pod elects
# it. Process-local by design — cross-process writers already serialize
# through the pid0-only commit protocol.
_RETENTION_LOCK = threading.Lock()


def _stitch(meta, req, readers, dtype, name="<var>"):
    """Assemble the requested [[start, stop], ...] extent of one var from
    its stored shards (which may tile it differently — resharding).
    Raises if the stored tiles do not cover the whole extent — a torn or
    truncated manifest must be a hard error, never silent garbage."""
    out = np.empty([b - a for a, b in req], dtype)
    want = int(np.prod([b - a for a, b in req])) if req else 1
    covered = 0
    for sh in meta["shards"]:
        offs = sh["offsets"]
        inter = [(max(a, ra), min(b, rb))
                 for (a, b), (ra, rb) in zip(offs, req)]
        if any(a >= b for a, b in inter):
            continue
        data = readers(sh["file"], sh["key"])
        src = tuple(slice(a - oa, b - oa)
                    for (a, b), (oa, _ob) in zip(inter, offs))
        dst = tuple(slice(a - ra, b - ra)
                    for (a, b), (ra, _rb) in zip(inter, req))
        out[dst] = data[src]
        covered += int(np.prod([b - a for a, b in inter])) if inter else 1
    if covered < want:
        raise ValueError(
            "checkpoint shards for %r cover only %d of %d elements of "
            "extent %r — manifest is torn or truncated" %
            (name, covered, want, req))
    return out


def _ckpt_logger():
    import logging
    from .log_helper import get_logger
    return get_logger("paddle_tpu.io", logging.WARNING,
                      fmt="%(asctime)s-%(levelname)s: %(message)s")


def _classify_step_dir(dirname, step_dir):
    """Classify one step dir as ``("valid"|"corrupt"|"incomplete",
    reason)`` WITHOUT reading any shard array payload.

    Only manifest JSON and npz/zip central directories (member name
    lists) are touched — cheap enough for a supervisor to scrub a whole
    checkpoint history before tearing down training state. Statuses:

      valid       manifest committed and every referenced shard file
                  holds every referenced key (a healthy-but-NEWER
                  format is also "valid": never a quarantine candidate)
      incomplete  the commit point (manifest) never landed — an
                  in-flight or torn save; restorable data may exist in
                  an older step dir, never here
      corrupt     the manifest committed but is unparsable, or shard
                  files/keys it references are damaged or missing
    """
    full_dir = os.path.join(dirname, step_dir)
    manifest_path = os.path.join(full_dir, MANIFEST_FILE)
    if not os.path.isdir(full_dir):
        return "incomplete", "step dir is missing"
    if not os.path.exists(manifest_path):
        legacy = os.path.join(full_dir, PARAMS_FILE)
        if os.path.exists(legacy):
            try:   # legacy (format 0) layout: one host-gather npz —
                   # opening the handle reads only the zip directory
                with np.load(legacy, allow_pickle=False) as z:
                    z.files
                return "valid", None
            except Exception as e:
                return "corrupt", "unreadable legacy params file: %s" % e
        try:
            kids = os.listdir(full_dir)
        except OSError as e:   # pragma: no cover - permission damage
            return "corrupt", "unreadable step dir: %s" % e
        if any(k.startswith("shards_p") for k in kids):
            return ("incomplete", "shard files present but no manifest "
                    "— the commit never landed")
        return "incomplete", "no manifest or shard files"
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        if manifest.get("format_version", 0) > CKPT_FORMAT_VERSION:
            # healthy, just newer than this library — load_checkpoint
            # surfaces CheckpointFormatError and must NOT quarantine
            return "valid", ("format_version %s newer than supported %d"
                             % (manifest.get("format_version"),
                                CKPT_FORMAT_VERSION))
        needed = {}
        for meta in manifest["vars"].values():
            for sh in meta["shards"]:
                needed.setdefault(sh["file"], set()).add(sh["key"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        return "corrupt", "torn or malformed manifest: %s" % e
    for fname, keys in needed.items():
        try:
            with np.load(os.path.join(full_dir, fname),
                         allow_pickle=False) as z:
                missing = keys.difference(z.files)
        except Exception as e:
            return "corrupt", "unreadable shard file %s: %s" % (fname, e)
        if missing:
            return "corrupt", "shard file %s is missing keys %s" % (
                fname, sorted(missing))
    return "valid", None


def _scrub_step_dir(dirname, step_dir):
    """Return a corruption description if the step dir is damaged ON
    DISK (torn/unparsable manifest, missing shard files or npz keys),
    else None.

    load_checkpoint quarantines only on a positive scrub: a load that
    failed for a caller-side reason (e.g. a bad ``shardings`` entry)
    must re-raise, not destroy the whole valid checkpoint history one
    rename at a time."""
    status, reason = _classify_step_dir(dirname, step_dir)
    if status == "valid":
        return None
    return reason or status


def scrub_checkpoint(dirname):
    """Cheap supervisor-side scrub of a whole checkpoint directory.

    Classifies every ``step_N`` dir as valid / corrupt / incomplete
    WITHOUT loading shard array payloads (manifest JSON + npz member
    lists only), so a pod supervisor can pick the restore point BEFORE
    tearing down training state. Read-only: never renames or
    quarantines — validity agrees with ``load_checkpoint``'s quarantine
    logic because both run the same classifier (_classify_step_dir).

    Returns a report dict::

        {"dirname":   the scrubbed directory,
         "latest":    the 'latest' pointer's target (or None),
         "steps":     {step_no: {"dir", "status", "reason"}},
         "valid_steps":  sorted [int] this library could restore,
         "quarantined":  ["step_N.corrupt", ...] kept for forensics}

    ``valid_steps`` is what feeds
    ``coordination.Coordinator.elect_restore_step`` — the pod consensus
    is the max step every live host reports here.
    """
    report = {"dirname": dirname, "latest": None, "steps": {},
              "valid_steps": [], "quarantined": []}
    try:
        kids = sorted(os.listdir(dirname))
    except OSError:
        return report          # no checkpoint dir yet — nothing valid
    try:
        with open(os.path.join(dirname, "latest")) as f:
            report["latest"] = f.read().strip() or None
    except OSError:
        pass
    counts = {"valid": 0, "corrupt": 0, "incomplete": 0}
    # classification runs under the retention lock (shared with
    # _prune_step_dirs): a concurrent keep_last GC must not collect a
    # step between this scrub calling it valid and the pod electing it
    with _RETENTION_LOCK:
        for d in kids:
            if not d.startswith("step_"):
                continue
            if ".corrupt" in d:
                report["quarantined"].append(d)
                continue
            if not d.split("_", 1)[1].isdigit():
                continue
            status, reason = _classify_step_dir(dirname, d)
            counts[status] += 1
            step_no = _step_no(d)
            report["steps"][step_no] = {"dir": d, "status": status,
                                        "reason": reason}
            if status == "valid" and reason is None:
                # reason != None on a valid dir means "newer format" —
                # intact, but THIS library cannot restore it
                report["valid_steps"].append(step_no)
    report["valid_steps"].sort()
    from .framework import resilience
    resilience.record_event("scrub", dirname=dirname,
                            valid=counts["valid"],
                            corrupt=counts["corrupt"],
                            incomplete=counts["incomplete"])
    return report


def _quarantine_step_dir(dirname, step_dir, reason):
    """Rename a corrupt step dir to step_N.corrupt (first free suffix) so
    it is never picked again but stays on disk for forensics."""
    import jax
    if jax.process_index() != 0:  # pragma: no cover - needs multihost
        return
    src = os.path.join(dirname, step_dir)
    dst = src + ".corrupt"
    i = 0
    while os.path.exists(dst):
        i += 1
        dst = "%s.corrupt.%d" % (src, i)
    try:
        os.rename(src, dst)
    except OSError:  # already gone / racing restore — nothing to keep
        return
    _ckpt_logger().warning(
        "checkpoint %s is corrupt (%s) — quarantined as %s",
        src, reason, os.path.basename(dst))
    from .framework import resilience
    resilience.record_event("ckpt_quarantine", step_dir=step_dir,
                            reason=str(reason))


def _load_step_dir(dirname, step_dir, shardings):
    """Load one step dir; returns (step, {name: array}, feed_state) or
    raises on any corruption (missing/torn manifest, missing shard files
    or keys). Nothing is written to the scope here — a partial load must
    not poison live training state. feed_state is the manifest's
    dataset cursor (None when the save carried none, and always None
    for legacy format-0 dirs)."""
    import jax
    full_dir = os.path.join(dirname, step_dir)
    manifest_path = os.path.join(full_dir, MANIFEST_FILE)
    if not os.path.exists(manifest_path):
        # legacy (format 0) host-gather npz checkpoint
        arrays = _load_arrays(full_dir, PARAMS_FILE)
        out = {name.replace("__AT__", "@"): np.asarray(arr)
               for name, arr in arrays.items()}
        return int(step_dir.split("_")[1]), out, None

    with open(manifest_path) as f:
        manifest = json.load(f)
    if manifest.get("format_version", 0) > CKPT_FORMAT_VERSION:
        raise CheckpointFormatError(
            "checkpoint %s has format_version %s, newer than this "
            "library's %d" % (full_dir, manifest.get("format_version"),
                              CKPT_FORMAT_VERSION))
    handles, arrays_cache = {}, {}

    def readers(fname, key):
        # cache decoded ARRAYS, not just npz handles: with shardings=,
        # _stitch runs once per local device shard and NpzFile.__getitem__
        # re-decompresses the member on every access. _decode_member
        # transparently dequantizes q8-compressed payloads.
        if (fname, key) not in arrays_cache:
            if fname not in handles:
                handles[fname] = np.load(os.path.join(full_dir, fname),
                                         allow_pickle=False)
            arrays_cache[(fname, key)] = _decode_member(handles[fname],
                                                        key)
        return arrays_cache[(fname, key)]

    try:
        out = {}
        for name, meta in manifest["vars"].items():
            shape = tuple(meta["shape"])
            dtype = np.dtype(meta["dtype"])
            target = shardings.get(name)
            if target is not None:
                arr = jax.make_array_from_callback(
                    shape, target,
                    lambda idx, meta=meta, shape=shape, dtype=dtype,
                    name=name:
                    _stitch(meta, _offset_list(idx, shape), readers, dtype,
                            name))
            else:
                arr = _stitch(meta, [[0, d] for d in shape], readers,
                              dtype, name)
            out[name] = arr
    finally:
        for h in handles.values():
            h.close()
    return int(manifest["step"]), out, manifest.get("feed_state")


def checkpoint_dir_bytes(dirname, step):
    """(raw, wire) byte accounting of one committed step dir: ``raw``
    summed from the manifest's declared shapes/dtypes (what an
    uncompressed payload would hold), ``wire`` from the npz file sizes
    on disk. Cheap — manifest JSON + stat, no payload reads. Feeds the
    ``stateship`` byte counters when a sync checkpoint ships rejoin
    state. Raises on a missing/torn manifest (callers ship only
    scrub-valid dirs)."""
    full_dir = os.path.join(dirname, "step_%d" % int(step))
    with open(os.path.join(full_dir, MANIFEST_FILE)) as f:
        manifest = json.load(f)
    raw = 0
    for meta in manifest["vars"].values():
        size = int(np.prod(meta["shape"])) if meta["shape"] else 1
        raw += size * np.dtype(meta["dtype"]).itemsize
    wire = sum(os.path.getsize(os.path.join(full_dir, k))
               for k in os.listdir(full_dir) if k.endswith(".npz"))
    return raw, wire


def _step_no(step_dir):
    return int(step_dir.split("_")[1])


def load_checkpoint(executor, dirname, main_program=None, shardings=None,
                    step=None, scope=None, with_feed_state=False):
    """Restore the latest VALID checkpoint into the global scope.

    with_feed_state: when True, return ``(step, feed_state)`` instead of
    the bare step — feed_state is the dataset cursor the save persisted
    (see ``save_checkpoint(feed_state=)``), or None when the manifest
    carries none (pre-cursor and legacy checkpoints load unchanged).

    shardings: optional {var_name: jax.sharding.Sharding} — vars listed
    are materialized straight onto the CURRENT mesh via
    jax.make_array_from_callback (each process reads only the slices its
    devices need; works when the restore topology differs from the save
    topology).  Unlisted vars load as host arrays and are placed by the
    next CompiledProgram/Executor run, exactly like a cold start.

    step: restore EXACTLY this step (the pod-consensus path — every host
    must land on the quorum-elected step, so there is no fallback: any
    failure raises instead of silently restoring a different step, which
    would deadlock the pod's collectives on mismatched trajectories).

    scope: destination Scope (default the global scope).

    Resilience semantics (step=None): a corrupt/missing ``latest``
    pointer or a step dir with a torn manifest / missing shards does NOT
    fail the restore. The bad step dir is quarantined (renamed
    ``step_N.corrupt``) and the newest previous valid checkpoint is used
    instead; only when NO valid checkpoint remains does the original
    error surface.
    """
    import jax
    wait_for_pending_saves()   # an in-flight async commit must land first
    scope = scope if scope is not None else global_scope()
    if step is not None:
        got, out, fs = _load_step_dir(dirname, "step_%d" % int(step),
                                      shardings or {})
        for name, arr in out.items():
            scope.set_var(name, arr)
        return (got, fs) if with_feed_state else got
    latest = None
    try:
        with open(os.path.join(dirname, "latest")) as f:
            latest = f.read().strip() or None
    except OSError:
        _ckpt_logger().warning(
            "checkpoint dir %s has no readable 'latest' pointer — "
            "falling back to the newest step dir", dirname)
    others = sorted(
        (d for d in os.listdir(dirname)
         if d.startswith("step_") and d != latest
         and d.split("_", 1)[1].isdigit()),
        key=_step_no, reverse=True)
    candidates = ([latest] if latest is not None else []) + others
    if latest is not None and not os.path.isdir(
            os.path.join(dirname, latest)):
        _ckpt_logger().warning(
            "'latest' names missing checkpoint %s/%s — falling back",
            dirname, latest)
        candidates = others

    first_err = None
    for step_dir in candidates:
        try:
            step, out, fs = _load_step_dir(dirname, step_dir,
                                           shardings or {})
        except (OSError, ValueError, KeyError, IndexError) as e:
            reason = _scrub_step_dir(dirname, step_dir)
            if reason is None:
                # healthy on disk: the failure is caller-side (e.g. bad
                # shardings) — quarantining would eat valid history
                raise
            if first_err is None:
                first_err = e
            _quarantine_step_dir(dirname, step_dir, reason)
            continue
        for name, arr in out.items():
            scope.set_var(name, arr)
        if step_dir != latest and jax.process_index() == 0:
            # repair the pointer so later saves/loads agree on history
            _atomic_write(os.path.join(dirname, "latest"), step_dir)
        return (step, fs) if with_feed_state else step
    if first_err is not None:
        raise first_err
    raise FileNotFoundError("no checkpoint found under %s" % dirname)
