"""Module-path alias for fluid.device_worker (ref
python/paddle/fluid/device_worker.py)."""
from .trainer_factory import DeviceWorker, Hogwild, DownpourSGD, \
    Section  # noqa: F401

__all__ = ["DeviceWorker", "Hogwild", "DownpourSGD", "Section"]
