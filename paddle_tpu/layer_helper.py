"""LayerHelper — shared machinery for layer functions.

Reference parity: python/paddle/fluid/layer_helper.py + layer_helper_base.py.
Creates parameters (into startup+main programs), temp variables, appends ops
and activations, exactly mirroring the reference flow so fluid model code
ports 1:1.
"""
import copy

from .framework import unique_name
from .framework.program import (default_main_program,
                                default_startup_program)
from .initializer import (ConstantInitializer, XavierInitializer)
from .param_attr import ParamAttr


class LayerHelper(object):
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = self.kwargs.get("name", None)
        if name is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, *args, **kwargs):
        from .dygraph import base as _dy
        if _dy.enabled():
            return self._append_op_eager(*args, **kwargs)
        return self.main_program.current_block().append_op(*args, **kwargs)

    def _append_op_eager(self, type, inputs=None, outputs=None, attrs=None,
                         **_ignored):
        """Dygraph branch (reference layer_helper_base.py in_dygraph_mode):
        resolve input names to eager values, run the kernel now, bind the
        results onto the placeholder variables the layer already created."""
        from .dygraph import base as _dy
        from .dygraph.nn import run_op

        def _names(v):
            return [v] if not isinstance(v, (list, tuple)) else list(v)

        ins = {slot: [_dy.lookup_eager(getattr(n, "name", n))
                      for n in _names(names)]
               for slot, names in (inputs or {}).items()}
        binding = {slot: [_dy.lookup_eager(getattr(n, "name", n))
                          for n in _names(names)]
                   for slot, names in (outputs or {}).items()}
        return run_op(type, ins, attrs or {}, out_binding=binding)

    # ---- inputs ----------------------------------------------------------
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" %
                             self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr", None))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr", None))

    def multiple_param_attr(self, length):
        param_attr = self.param_attr
        if isinstance(param_attr, ParamAttr):
            param_attr = [param_attr]
        if len(param_attr) != 1 and len(param_attr) != length:
            raise ValueError("parameter number mismatch")
        elif len(param_attr) == 1 and length != 1:
            param_attr = [copy.deepcopy(param_attr[0]) for _ in range(length)]
        return param_attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        param_attrs = self.multiple_param_attr(len(inputs))
        for ipt, param_attr in zip(inputs, param_attrs):
            yield ipt, param_attr

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for each in inputs:
            if dtype is None:
                dtype = each.dtype
            elif dtype != each.dtype:
                raise ValueError("layer inputs have mixed dtypes: %s vs %s"
                                 % (dtype, each.dtype))
        return dtype

    # ---- parameter / var creation ---------------------------------------
    def create_parameter(self, attr, shape, dtype=None, is_bias=False,
                         default_initializer=None, stop_gradient=False):
        if attr is False:
            return None
        attr = attr if isinstance(attr, ParamAttr) else ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "w_0" if
                                                       not is_bias else "b_0"]))
        init = attr.initializer
        if init is None:
            init = default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias \
                else XavierInitializer()
        dtype = dtype or self.kwargs.get("dtype", "float32")
        shape = [int(s) for s in shape]

        main_block = self.main_program.global_block()
        startup_block = self.startup_program.global_block()
        kwargs = attr._to_kwargs()
        kwargs.pop("name", None)
        param = main_block.create_parameter(
            name=attr.name, shape=shape, dtype=dtype, **kwargs)
        sparam = startup_block.create_parameter(
            name=attr.name, shape=shape, dtype=dtype, **kwargs)
        init(sparam, startup_block)
        return param

    def create_variable_for_type_inference(self, dtype, shape=None,
                                           stop_gradient=False):
        from .dygraph import base as _dy
        if _dy.enabled():
            return _dy.EagerVariable(None, stop_gradient=stop_gradient)
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype, shape=shape, persistable=False,
            stop_gradient=stop_gradient)

    # alias used throughout fluid layers
    def create_tmp_variable(self, dtype, shape=None):
        return self.create_variable_for_type_inference(dtype, shape)

    def create_variable(self, *args, **kwargs):
        return self.main_program.current_block().create_var(*args, **kwargs)

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, stop_gradient=True, **kwargs)

    def create_or_get_global_variable(self, name, *args, **kwargs):
        blk = self.main_program.global_block()
        if blk.has_var(name):
            return blk.var(name)
        return self.create_global_variable(name=name, *args, **kwargs)

    def set_variable_initializer(self, var, initializer):
        sblock = self.startup_program.global_block()
        svar = sblock.create_var(name=var.name, shape=var.shape,
                                 dtype=var.dtype, persistable=True)
        initializer(svar, sblock)

    # ---- bias / activation ----------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if bias_attr is False or bias_attr is None and \
                self.kwargs.get("bias_attr") is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        b = self.create_parameter(bias_attr, shape=size,
                                  dtype=input_var.dtype, is_bias=True)
        if b is None:
            return input_var
        tmp = self.create_variable_for_type_inference(input_var.dtype,
                                                      input_var.shape)
        self.append_op(
            "elementwise_add",
            inputs={"X": [input_var.name], "Y": [b.name]},
            outputs={"Out": [tmp.name]},
            attrs={"axis": dim_start})
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act", None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        else:
            act = dict(act)
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(input_var.dtype,
                                                      input_var.shape)
        self.append_op(act_type, inputs={"X": [input_var.name]},
                       outputs={"Out": [tmp.name]}, attrs=act)
        return tmp
