"""Profiler.

Reference parity: python/paddle/fluid/profiler.py — but TPU profiling goes
through jax.profiler (XPlane traces viewable in TensorBoard/Perfetto).

Rides the framework.obs spans engine as well: ``annotate`` opens an obs
span alongside the jax TraceAnnotation (so user annotations land BOTH
inside the XLA trace and on the cross-process obs timeline), and
``profile_program`` records per-op obs spans — one merged
``tools/traceview.py`` timeline can therefore show user annotations,
executor phases, router/replica serving legs and coordination waits
together, with jax.profiler covering the XLA interior.
"""
import contextlib

import jax

from .framework import obs


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)
    try:
        with obs.span("profiler.trace", path=str(profile_path)):
            yield
    finally:
        jax.profiler.stop_trace()


def start_profiler(state="All", tracer_option=None,
                   profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()


def reset_profiler():
    pass


@contextlib.contextmanager
def annotate(name):
    with jax.profiler.TraceAnnotation(name):
        with obs.span(str(name)):
            yield


def profile_program(program, feed, scope=None, repeat=3, sorted_key="total",
                    top_k=30, print_table=True):
    """Per-op time attribution (the reference profiler's sorted op table,
    ref python/paddle/fluid/profiler.py stop_profiler output).

    The production Executor fuses the whole Program into ONE XLA
    computation, so per-op times don't exist there; this runs the
    program OP-BY-OP eagerly (like the reference's per-kernel timers),
    blocking after each op.  Absolute times are therefore pessimistic —
    use the table for *attribution* (which ops dominate), and the fused
    step for real throughput.  Returns rows of
    (op_type, calls, total_s, avg_s) sorted by ``sorted_key``
    ("total" | "calls" | "ave").
    """
    import time
    from collections import defaultdict

    import numpy as np

    from .framework.executor import _persistable_names, _want_vjp_set
    from .framework.trace import TraceContext, trace_op, _rng_tag
    from .framework.scope import global_scope

    scope = scope or global_scope()
    totals = defaultdict(float)
    calls = defaultdict(int)
    for rep in range(repeat):
        env = {}
        for n in _persistable_names(program):
            v = scope.find_var(n)
            if v is not None:
                env[n] = v
        for k, v in (feed or {}).items():
            env[k] = jax.numpy.asarray(v)
        ctx = TraceContext(program, jax.random.PRNGKey(rep),
                           _want_vjp_set(program))
        block = program.global_block()
        for i, op in enumerate(block.ops):
            t0 = time.perf_counter()
            with obs.span("op.%s" % op.type, repeat=rep):
                trace_op(op, env, ctx, _rng_tag(block, i))
                for out_name in op.output_names():
                    v = env.get(out_name)
                    if hasattr(v, "block_until_ready"):
                        v.block_until_ready()
            dt = time.perf_counter() - t0
            if rep > 0:  # first pass pays compilation; attribute after
                totals[op.type] += dt
                calls[op.type] += 1
    rows = [(t, calls[t], totals[t], totals[t] / max(calls[t], 1))
            for t in totals]
    key_idx = {"total": 2, "calls": 1, "ave": 3}[sorted_key]
    rows.sort(key=lambda r: -r[key_idx])
    rows = rows[:top_k]
    if print_table:
        print("%-28s %8s %12s %12s" % ("Op", "Calls", "Total(s)",
                                       "Avg(s)"))
        for t, c, tot, avg in rows:
            print("%-28s %8d %12.6f %12.6f" % (t, c, tot, avg))
    return rows


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """ref profiler.cuda_profiler — no CUDA here; delegates to the XLA
    trace so existing scripts still produce a usable profile."""
    import warnings
    warnings.warn("cuda_profiler on paddle_tpu records a jax.profiler "
                  "trace instead of a CUDA profile")
    jax.profiler.start_trace(output_file or "/tmp/paddle_tpu_profile")
    try:
        yield
    finally:
        jax.profiler.stop_trace()
