"""Profiler.

Reference parity: python/paddle/fluid/profiler.py — but TPU profiling goes
through jax.profiler (XPlane traces viewable in TensorBoard/Perfetto).
"""
import contextlib

import jax


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def start_profiler(state="All", tracer_option=None,
                   profile_path="/tmp/paddle_tpu_profile"):
    jax.profiler.start_trace(profile_path)


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()


def reset_profiler():
    pass


@contextlib.contextmanager
def annotate(name):
    with jax.profiler.TraceAnnotation(name):
        yield
