"""paddle.check_import_scipy parity (ref python/paddle/
check_import_scipy.py): Windows-only scipy DLL sanity probe."""

__all__ = ["check_import_scipy"]


def check_import_scipy(OsName):
    """On Windows ('nt') verify scipy.io imports, surfacing the usual
    missing-VC++-runtime cause; a no-op elsewhere (TPU hosts are Linux)."""
    if OsName != "nt":
        return
    try:
        import scipy.io  # noqa: F401
    except ImportError as e:
        raise ImportError(
            str(e) + "\nscipy.io failed to import on Windows — usually a "
            "missing Visual C++ runtime; install the MSVC redistributable "
            "and retry")
