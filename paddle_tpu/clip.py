"""Gradient / error clipping.

Reference parity: python/paddle/fluid/clip.py (ErrorClipByValue,
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
set_gradient_clip). Global-norm clip builds the norm reduction in-graph so
it fuses into the train step (and under data parallelism the norm is over
the full global gradient because grads are already mesh-reduced by XLA).
"""
from .layer_helper import LayerHelper
from . import layers


class BaseErrorClipAttr(object):
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def _append_clip_op(self, block, grad_name):
        block.append_op("clip", inputs={"X": [grad_name]},
                        outputs={"Out": [grad_name]},
                        attrs={"min": self.min, "max": self.max,
                               "op_role": "backward"})


class GradientClipBase(object):
    def _process(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(-max if min is None else min)

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.clip(g, self.min, self.max)))
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.clip_by_norm(g, self.clip_norm)))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def _process(self, params_grads):
        helper = LayerHelper("global_norm_clip")
        sq_norms = []
        for p, g in params_grads:
            if g is None:
                continue
            sq = helper.create_variable_for_type_inference("float32", (1,))
            helper.append_op("squared_l2_norm", inputs={"X": [g.name]},
                             outputs={"Out": [sq.name]},
                             attrs={"op_role": "optimize"})
            sq_norms.append(sq)
        if not sq_norms:
            return params_grads
        total = layers.sums(sq_norms) if len(sq_norms) > 1 else sq_norms[0]
        global_norm = layers.sqrt(total)
        max_norm = layers.fill_constant([1], "float32", self.clip_norm)
        scale = layers.elementwise_div(
            max_norm, layers.elementwise_max(global_norm, max_norm))
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, layers.elementwise_mul(g, scale)))
        return out


_gradient_clip = None


def set_gradient_clip(clip, param_list=None, program=None):
    """Reference parity: fluid.clip.set_gradient_clip."""
    global _gradient_clip
    _gradient_clip = clip
    if param_list:
        for p in param_list:
            p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads):
    clip = _gradient_clip
    per_param = any(getattr(p, "gradient_clip_attr", None) is not None
                    for p, _ in params_grads)
    if clip is None and not per_param:
        return params_grads
    if per_param and not isinstance(clip, GradientClipByGlobalNorm):
        out = []
        for p, g in params_grads:
            c = getattr(p, "gradient_clip_attr", None) or clip
            if c is None or g is None:
                out.append((p, g))
            else:
                out.extend(c._process([(p, g)]))
        return out
    return clip._process(params_grads)


def error_clip_callback(block, op):
    pass
