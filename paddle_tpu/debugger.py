"""Program visualization and debugging helpers.

Reference parity: python/paddle/fluid/debugger.py (pprint_program +
graphviz drawing via net_drawer.py). TPU-native additions: the op graph is
rendered straight from the Program IR (no proto), and the real "what runs
on the chip" view is Executor.dump_hlo (framework/executor.py), which
returns the single fused StableHLO/HLO module for a step.
"""

__all__ = ["pprint_program", "draw_program", "draw_block_graphviz"]


def pprint_program(program, print_fn=print):
    """Pretty-print a Program (reference pprint_program)."""
    print_fn(program.to_string())


def _quote(s):
    return '"%s"' % str(s).replace('"', '\\"')


def draw_block_graphviz(block, highlights=None, path=None):
    """Render one block's op/var graph as graphviz DOT text (reference
    debugger.draw_block_graphviz). Ops are boxes, variables are ellipses,
    edges follow input/output slots. Writes to `path` if given; always
    returns the DOT text. No graphviz runtime needed — the text renders
    with any `dot` binary or web viewer."""
    highlights = set(highlights or ())
    lines = ["digraph G {", "  rankdir=TB;"]
    seen_vars = {}

    def var_node(name):
        if name not in seen_vars:
            vid = "var_%d" % len(seen_vars)
            seen_vars[name] = vid
            var = block._find_var_recursive(name)
            label = name
            if var is not None and var.shape is not None:
                label = "%s\\n%s %s" % (name, var.dtype,
                                        tuple(var.shape))
            style = "filled" if name in highlights else "solid"
            lines.append(
                '  %s [label=%s, shape=ellipse, style=%s, '
                'fillcolor=lightpink];' % (vid, _quote(label), style))
        return seen_vars[name]

    for i, op in enumerate(block.ops):
        oid = "op_%d" % i
        lines.append(
            '  %s [label=%s, shape=box, style=filled, '
            'fillcolor=lightblue];' % (oid, _quote(op.type)))
        for slot, names in sorted(op.inputs.items()):
            for name in names:
                lines.append('  %s -> %s [label=%s];'
                             % (var_node(name), oid, _quote(slot)))
        for slot, names in sorted(op.outputs.items()):
            for name in names:
                lines.append('  %s -> %s [label=%s];'
                             % (oid, var_node(name), _quote(slot)))
    lines.append("}")
    text = "\n".join(lines)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def draw_program(program, path=None, block_idx=0, highlights=None):
    """DOT graph of `program`'s block `block_idx` (reference
    net_drawer.draw_graph / debugger entry point)."""
    return draw_block_graphviz(program.blocks[block_idx],
                               highlights=highlights, path=path)
