"""Deployable serving artifacts — "train here, serve anywhere".

Reference parity: the C++ predictor API
(/root/reference/paddle/fluid/inference/api/paddle_api.h:148
PaddlePredictor/ZeroCopyTensor and analysis_predictor.h:47) lets trained
models serve from non-Python daemons. The TPU-native equivalent is a
serialized StableHLO artifact via jax.export: the pruned inference
Program is traced ONCE into a single XLA computation with the trained
weights baked in as constants, then serialized to

  serving/meta.json          feed/fetch names, shapes, dtypes, buckets
  serving/export_b{N}.bin    jax.export bytes (deserialize + call)
  serving/module_b{N}.mlir   StableHLO text — a C++ PjRt client can
                             compile this module directly, no Python

One export per batch bucket (XLA computations are static-shape; the
loader pads requests up to the nearest bucket, same policy as
inference.Predictor's compile cache).
"""
import json
import os

import numpy as np

MODULE_SUBDIR = "serving"
# v1: feed_batch_dynamic (bool per feed). v2: feed_batch_factor /
# fetch_batch_factor (ints; dim0 = factor * batch, 0 = static).
# v3: optional weight_compress="q8" — weights ship as a block-quantized
# int8 npz (the PR 6 checkpoint codec) and enter the exported
# computation as ARGUMENTS instead of baked constants. LOSSY, so only
# q8 exports are stamped v3: an older library refuses them instead of
# serving garbage, while plain exports stay v2-readable everywhere.
SERVING_FORMAT_VERSION = 3
WEIGHTS_Q8_FILE = "weights_q8.npz"


def _infer_fn(program, feed_names, fetch_names, scope,
              weights_as_args=False):
    """Close the trained weights over a pure (feeds) -> fetches function.

    jax.export turns closure arrays into embedded constants, which is
    exactly the frozen-artifact contract: the .bin is self-contained.

    ``weights_as_args=True`` is the quantized-artifact variant: the
    weights become LEADING arguments (sorted by name) instead of baked
    constants, so the .bin stays weight-free and the int8 weight file
    shipped beside it is the only weight payload. Returns
    ``(fn, weight_names, weight_arrays)`` in that mode."""
    import jax
    from .framework import executor as ex_mod
    from .framework.trace import TraceContext, trace_block

    persistable = ex_mod._persistable_names(program)
    state = {n: scope.find_var(n) for n in sorted(persistable)
             if scope.find_var(n) is not None}

    if not weights_as_args:
        def fn(*feeds):
            env = dict(state)
            env.update(zip(feed_names, feeds))
            ctx = TraceContext(program, jax.random.PRNGKey(0),
                               frozenset())
            trace_block(program.global_block(), env, ctx)
            return tuple(env[n] for n in fetch_names)

        return fn

    weight_names = sorted(state)

    def wfn(*args):
        env = dict(zip(weight_names, args[:len(weight_names)]))
        env.update(zip(feed_names, args[len(weight_names):]))
        ctx = TraceContext(program, jax.random.PRNGKey(0), frozenset())
        trace_block(program.global_block(), env, ctx)
        return tuple(env[n] for n in fetch_names)

    return wfn, weight_names, \
        [np.asarray(state[n]) for n in weight_names]


def infer_batch_factors(dyn_dims, overrides=None):
    """Shared batch-factor inference (serving export AND the in-process
    Predictor): `dyn_dims` is [(name, dim0)] for the batch-dynamic
    feeds. A feed's dim0 = factor * batch; the smallest dim0 is taken as
    the batch unless `overrides` ({name: factor}) pins a feed — then the
    batch derives from the overridden feeds (they must agree). Returns
    ({name: factor}, batch). batch 0 (empty request) gives factor 1 to
    every non-overridden feed."""
    overrides = overrides or {}
    if not dyn_dims:
        return {}, None
    base = None
    for name, d0 in dyn_dims:
        if name in overrides:
            f = int(overrides[name])
            if f <= 0 or d0 % f:
                raise ValueError(
                    "feed %r dim0 %d is not a multiple of its declared "
                    "batch factor %r" % (name, d0, overrides[name]))
            b2 = d0 // f
            if base is None:
                base = b2
            elif b2 != base:
                raise ValueError(
                    "overridden feeds disagree on the batch: %r implies "
                    "%d, earlier feeds %d" % (name, b2, base))
    if base is None:
        base = min(d0 for _, d0 in dyn_dims)
    factors = {}
    for name, d0 in dyn_dims:
        if name in overrides:
            factors[name] = int(overrides[name])
        elif base == 0:
            factors[name] = 1
        else:
            if d0 % base:
                raise ValueError(
                    "feed %r leading dim %d is not a multiple of the "
                    "batch %d" % (name, d0, base))
            factors[name] = d0 // base
    return factors, base


def _feed_factors(program, feed_names, example_feed, overrides=None):
    """Per-feed batch factors: feed i's leading dim is factor[i] *
    request_batch (0 = static feed). Factor 1 is the default for
    batch-dynamic feeds; an example feed dict refines it for feeds whose
    leading dim scales as a MULTIPLE of the batch (e.g. BERT's flat
    mask_pos with dim0 = batch * max_preds) — inference takes the
    SMALLEST dynamic leading dim as the batch, so at least one dynamic
    feed must carry dim0 == batch; if none does, pass explicit factors
    via `overrides` ({feed_name: factor})."""
    blk = program.global_block()
    dyn = []
    for name in feed_names:
        shape = list(blk.var(name).shape)
        dyn.append(bool(shape) and shape[0] == -1)
    if not any(dyn):
        return [0] * len(feed_names)
    overrides = overrides or {}
    if example_feed is None:
        return [overrides.get(n, 1) if d else 0
                for n, d in zip(feed_names, dyn)]
    dyn_dims = [(n, np.asarray(example_feed[n]).shape[0])
                for n, d in zip(feed_names, dyn) if d]
    fmap, _ = infer_batch_factors(dyn_dims, overrides)
    return [fmap[n] if d else 0 for n, d in zip(feed_names, dyn)]


def _feed_avals(program, feed_names, batch, factors):
    """ShapeDtypeStructs for the feeds at one bucket size; a leading -1
    (append_batch_size) dim becomes factor * bucket batch."""
    import jax
    from .framework.dtypes import to_jax_dtype
    blk = program.global_block()
    avals = []
    for name, factor in zip(feed_names, factors):
        var = blk.var(name)
        shape = list(var.shape)
        if factor:
            shape[0] = batch * factor
        if any(s is None or s < 0 for s in shape):
            raise ValueError(
                "serving export: feed %r has non-batch dynamic dims %s — "
                "XLA serving artifacts are static-shape" % (name, shape))
        avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                          to_jax_dtype(var.dtype)))
    return avals


def export_serving_artifact(dirname, feeded_var_names, target_vars,
                            executor=None, main_program=None,
                            batch_sizes=(1, 8, 32), scope=None,
                            pruned_program=None, example_feed=None,
                            feed_batch_factors=None,
                            weight_compress=None):
    """Freeze + export the inference program as StableHLO.

    Writes under dirname/serving/. target_vars may be Variables or names.
    pruned_program skips the clone+prune when the caller (e.g.
    save_inference_model) already froze the program. example_feed (one
    representative feed dict) teaches the export which batch-dynamic
    feeds scale as a MULTIPLE of the request batch (BERT's flat mask_pos
    = batch * max_preds); without it every dynamic feed is assumed
    factor 1. Returns the list of written export paths.

    weight_compress="q8" writes the QUANTIZED artifact layout: instead
    of baking fp32 weights into every per-bucket .bin as constants, the
    weights enter the computation as arguments and ship ONCE as
    block-quantized int8 + per-block fp32 scales (the PR 6 checkpoint
    codec, serving/weights_q8.npz) — the artifact a rolling deploy
    ships shrinks by roughly the weight bytes' 4x. LOSSY: outputs match
    the fp32 artifact only to quantization tolerance, so q8 is strictly
    opt-in and the meta is stamped format_version 3 (older loaders
    refuse it rather than serve garbage)."""
    import jax
    from jax import export as jax_export
    from .framework.program import default_main_program
    from .framework.scope import global_scope

    if not batch_sizes:
        raise ValueError("serving export needs at least one batch size")
    if weight_compress not in (None, "q8"):
        raise ValueError("serving export weight_compress must be None "
                         "or 'q8', got %r" % (weight_compress,))
    scope = scope or global_scope()
    target_names = [getattr(v, "name", v) for v in target_vars]
    if pruned_program is not None:
        pruned = pruned_program
    else:
        program = main_program or default_main_program()
        test_prog = program.clone(for_test=True)
        pruned = test_prog._prune(list(feeded_var_names), target_names)

    # build the whole artifact in a temp dir and swap it in at the end:
    # an interrupted re-export must never leave a loadable mix of old and
    # new exports (same commit-point discipline as io._atomic_write)
    final_dir = os.path.join(dirname, MODULE_SUBDIR)
    out_dir = final_dir + ".tmp.%d" % os.getpid()
    if os.path.exists(out_dir):
        import shutil
        shutil.rmtree(out_dir)
    os.makedirs(out_dir)
    if weight_compress == "q8":
        fn, weight_names, weight_arrays = _infer_fn(
            pruned, list(feeded_var_names), target_names, scope,
            weights_as_args=True)
        from .io import _decode_member, _encode_payload
        payload = _encode_payload(
            dict(zip(weight_names, weight_arrays)), "q8")
        np.savez(os.path.join(out_dir, WEIGHTS_Q8_FILE), **payload)
        # the exported computation is traced against (and will be FED)
        # the dequantized weights — quantize/dequantize here so export-
        # time eval_shape and load-time serving see the same values
        with np.load(os.path.join(out_dir, WEIGHTS_Q8_FILE)) as z:
            weight_arrays = [_decode_member(z, n) for n in weight_names]
        weight_avals = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in weight_arrays]
    else:
        fn = _infer_fn(pruned, list(feeded_var_names), target_names,
                       scope)
        weight_names, weight_avals = [], []

    factors = _feed_factors(pruned, feeded_var_names, example_feed,
                            overrides=feed_batch_factors)
    dynamic = any(factors)
    buckets = sorted(set(batch_sizes)) if dynamic else [0]

    # which OUTPUTS scale with the batch, and by what factor: compare
    # abstract output shapes at two batch sizes (jax.eval_shape — no
    # compile). Recorded at export so the loader never guesses from
    # runtime shapes (a static dim that happens to equal batch*f must
    # not get sliced).
    fetch_factors = [0] * len(target_names)
    if dynamic:
        o1 = jax.eval_shape(fn, *(weight_avals + _feed_avals(
            pruned, feeded_var_names, 1, factors)))
        o2 = jax.eval_shape(fn, *(weight_avals + _feed_avals(
            pruned, feeded_var_names, 2, factors)))
        for i, (s1, s2) in enumerate(zip(o1, o2)):
            if s1.shape and s2.shape and s2.shape[0] != s1.shape[0]:
                fetch_factors[i] = s2.shape[0] - s1.shape[0]

    written, bucket_meta = [], {}
    for b in buckets:
        avals = _feed_avals(pruned, feeded_var_names, b or 1, factors)
        exported = jax_export.export(jax.jit(fn))(*(weight_avals
                                                    + avals))
        blob = exported.serialize()
        bin_path = os.path.join(out_dir, "export_b%d.bin" % b)
        with open(bin_path, "wb") as f:
            f.write(blob)
        with open(os.path.join(out_dir, "module_b%d.mlir" % b), "w") as f:
            f.write(exported.mlir_module())
        written.append(bin_path)
        bucket_meta[str(b)] = {
            "feeds": [{"name": n, "shape": list(a.shape),
                       "dtype": np.dtype(a.dtype).name}
                      for n, a in zip(feeded_var_names, avals)]}

    # plain exports stay stamped v2 so every older loader keeps reading
    # them; only the lossy q8 layout needs the v3 fence
    meta = {"format_version": 3 if weight_compress else 2,
            "feed_var_names": list(feeded_var_names),
            "fetch_var_names": target_names,
            "dynamic_batch": dynamic,
            "feed_batch_factor": factors,
            "fetch_batch_factor": fetch_factors,
            "buckets": bucket_meta}
    if weight_compress:
        meta["weight_compress"] = weight_compress
        meta["weight_names"] = weight_names
    with open(os.path.join(out_dir, "meta.json"), "w") as f:
        json.dump(meta, f, indent=1)
    import shutil
    if os.path.exists(final_dir):
        shutil.rmtree(final_dir)
    os.rename(out_dir, final_dir)
    return [p.replace(out_dir, final_dir) for p in written]


class ServingPredictor(object):
    """Thin loader for the StableHLO artifact: deserialize + call.

    Python twin of the C++ load path (a non-Python service compiles
    module_b{N}.mlir with PjRt instead). Pads requests up to the nearest
    exported bucket and slices results back — the inference.Predictor
    contract.

    Resilience (framework/resilience.py):
      * ``run(..., deadline_s=)`` bounds each request wall-clock —
        host-side slowness, cold-bucket compiles and device waits alike
        — via resilience.run_with_deadline -> DeadlineExceededError.
      * ``max_in_flight`` load-sheds excess concurrency with
        ServerOverloadedError instead of queue collapse.
      * degraded mode: when a COLD bucket (first request compiles it)
        blows the deadline and a warm larger bucket exists, the request
        is padded up and served from the warm bucket while the abandoned
        compile finishes in the background. The fallback is new backend
        work and claims its own in-flight slot — under cap pressure it
        sheds rather than exceed the cap.
    """

    def __init__(self, dirname, max_in_flight=None, deadline_s=None):
        import threading
        from jax import export as jax_export
        out_dir = os.path.join(dirname, MODULE_SUBDIR)
        self._max_in_flight = max_in_flight
        self._deadline_s = deadline_s
        self._in_flight = 0
        self._lock = threading.Lock()
        self._warm = set()   # buckets that served (=> compiled) already
        # per-replica health counters (the orchestrator-facing twin of
        # the process-global resilience event log)
        self._stats = {"requests": 0, "deadline_misses": 0, "sheds": 0,
                       "degraded_serves": 0, "errors": 0}
        with open(os.path.join(out_dir, "meta.json")) as f:
            self._meta = json.load(f)
        if self._meta["format_version"] > SERVING_FORMAT_VERSION:
            raise ValueError(
                "serving artifact %s has format_version %d, newer than "
                "this library's %d"
                % (dirname, self._meta["format_version"],
                   SERVING_FORMAT_VERSION))
        # progcheck at load (framework/analysis.py): when the export
        # shipped its Program IR (__model__.json beside serving/), a
        # corrupt program refuses to LOAD — so a bad artifact fails the
        # rolling-deploy drain step (the replica returns to rotation on
        # its old weights) instead of the first live request. Disable
        # only via PADDLE_TPU_VERIFY=off (debug escape hatch).
        self._verify_exported_program(dirname)
        if "feed_batch_factor" not in self._meta:
            # v1 artifacts: booleans, factor 1 semantics; outputs were
            # sliced when dim0 == bucket (factor 1)
            dyn = self._meta.get("feed_batch_dynamic", [])
            self._meta["feed_batch_factor"] = [1 if d else 0 for d in dyn]
            self._meta["fetch_batch_factor"] = [
                1] * len(self._meta["fetch_var_names"])
        self._feed_names = self._meta["feed_var_names"]
        self._fetch_names = self._meta["fetch_var_names"]
        # quantized artifacts (v3, weight_compress="q8") ship the
        # weights OUTSIDE the .bin as block-quantized int8; dequantize
        # once at load and prepend them to every exported call — the
        # computation took them as leading arguments at export
        wc = self._meta.get("weight_compress")
        if wc not in (None, "q8"):
            raise ValueError(
                "serving artifact %s has unknown weight_compress %r"
                % (dirname, wc))
        self._weight_args = []
        if wc == "q8":
            from .io import _decode_member
            with np.load(os.path.join(out_dir, WEIGHTS_Q8_FILE)) as z:
                self._weight_args = [
                    _decode_member(z, n)
                    for n in self._meta["weight_names"]]
        self._fns = {}
        for key in self._meta["buckets"]:
            with open(os.path.join(out_dir, "export_b%s.bin" % key),
                      "rb") as f:
                self._fns[int(key)] = jax_export.deserialize(f.read())

    @property
    def weight_compress(self):
        """None for a classic baked-constants artifact, "q8" when the
        weights ride beside the export as block-quantized int8."""
        return self._meta.get("weight_compress")

    def _call_bucket(self, b, feeds):
        """Invoke one exported bucket, prepending the artifact's
        dequantized weights when it shipped them as arguments."""
        if self._weight_args:
            return self._fns[b].call(*(self._weight_args
                                       + list(feeds)))
        return self._fns[b].call(*feeds)

    @staticmethod
    def _verify_exported_program(dirname):
        from .framework import analysis
        if analysis.env_verify_mode() == "off":
            return
        model_path = os.path.join(dirname, "__model__.json")
        if not os.path.exists(model_path):
            return    # serving-only artifact: no IR shipped to vet
        try:
            with open(model_path) as f:
                meta = json.load(f)
            result = analysis.verify_model_meta(meta)
        except (ValueError, TypeError) as e:
            raise ValueError(
                "serving artifact %s ships a corrupt program IR "
                "(%s) — refusing to load it" % (dirname, e))
        analysis.report(result, mode="strict", source="serving_load")
        if result.errors():
            raise ValueError(
                "serving artifact %s failed program verification — "
                "refusing to load it:\n%s" % (dirname, result.summary()))

    def get_input_names(self):
        return list(self._feed_names)

    def get_output_names(self):
        return list(self._fetch_names)

    def feed_batch_factors(self):
        """{feed name: batch factor} — feed i's leading dim is
        factor * request_batch (0 = static feed). This is the export's
        recorded contract; the fleet router uses it to coalesce and
        split requests without guessing from runtime shapes."""
        return dict(zip(self._feed_names,
                        self._meta["feed_batch_factor"]))

    def fetch_batch_factors(self):
        """{fetch name: batch factor} — output i's leading dim is
        factor * request_batch (0 = static output)."""
        return dict(zip(self._fetch_names,
                        self._meta["fetch_batch_factor"]))

    def feed_dtypes(self):
        """{feed name: numpy dtype name} from the export's bucket
        specs — what a JSON-transported request must be cast back to
        before the exported computation is called."""
        first = self._meta["buckets"][sorted(self._meta["buckets"])[0]]
        return {f["name"]: f["dtype"] for f in first["feeds"]}

    def feed_inner_shapes(self):
        """{feed name: fixed dims}: for a batch-dynamic feed the
        trailing dims (everything after the batch-scaled leading dim);
        for a static feed (factor 0) the FULL shape. What lets a
        router validate a request's whole shape at admission — a
        malformed request must be a client error there, never a
        replica-side failure shared with its coalesced siblings."""
        first = self._meta["buckets"][sorted(self._meta["buckets"])[0]]
        factors = self.feed_batch_factors()
        out = {}
        for f in first["feeds"]:
            shape = list(f["shape"])
            out[f["name"]] = shape[1:] if factors.get(f["name"]) \
                else shape
        return out

    @property
    def dynamic_batch(self):
        return bool(self._meta["dynamic_batch"])

    @property
    def max_bucket(self):
        """Largest exported batch bucket (0 for a static artifact)."""
        return max(self._fns)

    def _bump(self, key):
        with self._lock:
            self._stats[key] += 1

    def health(self):
        """Readiness/liveness snapshot for orchestrator probes.

        JSON-ready dict (tools/serving_probe.py serves it on the command
        line). ``ready`` is the rotation signal: True only while the
        replica can take traffic at full quality NOW — every exported
        bucket warm (a cold bucket means live traffic eats a compile)
        and the in-flight cap not saturated. ``status`` explains why
        not: "cold" (warm it up), "saturated" (scale out / back off),
        "degraded" (serving, but deadline misses, warm-bucket fallbacks
        or hard errors happened — rotate when persistent), else "ok". The
        counters are cumulative for THIS replica's lifetime."""
        with self._lock:
            warm = sorted(self._warm)
            stats = dict(self._stats)
            in_flight = self._in_flight
        buckets = sorted(self._fns)
        cold = [b for b in buckets if b not in warm]
        saturated = self._max_in_flight is not None \
            and in_flight >= self._max_in_flight
        if saturated:
            status = "saturated"
        elif cold:
            status = "cold"
        elif stats["degraded_serves"] or stats["deadline_misses"] \
                or stats["errors"]:
            status = "degraded"
        else:
            status = "ok"
        snapshot = {"live": True, "ready": not saturated and not cold,
                    "status": status, "in_flight": in_flight,
                    "max_in_flight": self._max_in_flight,
                    "buckets": buckets, "warm_buckets": warm,
                    "cold_buckets": cold}
        snapshot.update(stats)
        return snapshot

    def _bucket(self, n):
        for b in sorted(self._fns):
            if n <= b:
                return b
        raise ValueError(
            "request batch %d exceeds the largest exported bucket %d — "
            "re-export with a larger batch_sizes entry"
            % (n, max(self._fns)))

    # -- admission control ------------------------------------------------
    @property
    def in_flight(self):
        """LIVE backend work, not callers inside run(): a request whose
        deadline expired still occupies its slot until the orphaned
        worker actually finishes — a timeout/retry storm must not stack
        unbounded concurrent device work behind a cap reading 0."""
        return self._in_flight

    def _acquire_slot(self):
        """Claim an in-flight slot (ServerOverloadedError when full).
        Returns an idempotent release callable; the RUNNING work calls
        it on completion, so abandoned deadline workers keep their slot
        until they exit."""
        from .framework import resilience
        if self._max_in_flight is None:
            return lambda: None
        with self._lock:
            if self._in_flight >= self._max_in_flight:
                self._stats["sheds"] += 1
                resilience.record_event(
                    "shed", in_flight=self._in_flight,
                    cap=self._max_in_flight)
                raise resilience.ServerOverloadedError(
                    "serving predictor is at its in-flight cap "
                    "(%d) — shedding load; retry with backoff"
                    % self._max_in_flight)
            self._in_flight += 1
        released = []

        def release():
            with self._lock:
                if not released:
                    released.append(True)
                    self._in_flight -= 1
        return release

    # -- request batch / bucket handling ----------------------------------
    def _request_batch(self, inputs):
        """Request batch from the feeds' recorded batch factors (feed i's
        dim0 = factor_i * batch) — never from dict order."""
        factors = self._meta["feed_batch_factor"]
        n = None
        for name, f in zip(self._feed_names, factors):
            if f:
                got = np.asarray(inputs[name]).shape[0]
                if got % f:
                    raise ValueError(
                        "feed %r has %d rows, not a multiple of its "
                        "batch factor %d" % (name, got, f))
                if n is None:
                    n = got // f
                elif got // f != n:
                    raise ValueError(
                        "batch-dynamic feeds disagree on batch size: "
                        "feed %r implies batch %d, earlier feeds %d"
                        % (name, got // f, n))
        return n

    def warmup(self, buckets=None):
        """Compile (and mark warm) the given buckets — all by default.
        Run at deploy time so live traffic never eats a cold compile."""
        for b in sorted(self._fns) if buckets is None else buckets:
            spec = self._meta["buckets"][str(b)]["feeds"]
            feeds = [np.zeros(f["shape"], dtype=np.dtype(f["dtype"]))
                     for f in spec]
            for o in self._call_bucket(b, feeds):
                np.asarray(o)
            self._mark_warm(b)

    def _mark_warm(self, b):
        # orphaned deadline workers finish compiles in the background and
        # land here concurrently with caller-thread reads — lock both
        with self._lock:
            self._warm.add(b)

    def _warm_fallback_bucket(self, n):
        """Smallest WARM bucket that fits a batch-n request, or None."""
        with self._lock:
            warm = sorted(self._warm)
        fits = [b for b in warm if b >= (n or 0)]
        return fits[0] if fits else None

    def _run_impl(self, inputs, force_bucket=None):
        from .framework.resilience import fire
        # injection point: a chaos 'slow' fault sleeps INSIDE the
        # deadline-bounded region; 'error' raises like a dying backend
        actions = fire("serve", what="ServingPredictor.run")
        if actions.get("slow_s"):
            import time
            time.sleep(actions["slow_s"])
        if not self._meta["dynamic_batch"]:
            outs = self._call_bucket(
                0, [np.asarray(inputs[n]) for n in self._feed_names])
            outs = [np.asarray(o) for o in outs]
            self._mark_warm(0)
            return outs
        factors = self._meta["feed_batch_factor"]
        n = self._request_batch(inputs)
        b = self._bucket(n) if force_bucket is None else force_bucket
        feeds = []
        for name, f in zip(self._feed_names, factors):
            arr = np.asarray(inputs[name])
            if f and arr.shape[0] != b * f:
                pad = [(0, b * f - arr.shape[0])] + \
                    [(0, 0)] * (arr.ndim - 1)
                arr = np.pad(arr, pad)
            feeds.append(arr)
        outs = self._call_bucket(b, feeds)
        self._mark_warm(b)
        # slice batch-scaled outputs per the EXPORT-time factors — never
        # guessed from runtime shapes (a static dim that happens to
        # equal b*f must not be truncated)
        fetch_factors = self._meta["fetch_batch_factor"]
        sliced = []
        for o, f in zip(outs, fetch_factors):
            o = np.asarray(o)
            if f and np.ndim(o) > 0 and o.shape[0] == b * f:
                o = o[:n * f]
            sliced.append(o)
        return sliced

    def run(self, inputs, deadline_s=None, degraded_ok=True):
        """inputs: dict name -> array (or list aligned with feed names).
        Returns list of np arrays aligned with fetch names.

        deadline_s (defaults to the constructor's): wall-clock budget for
        THIS request; DeadlineExceededError past it. degraded_ok: a
        deadline miss on a cold bucket falls back to a warm larger
        bucket when one exists (recorded as a 'degraded' event)."""
        from .framework import resilience
        if isinstance(inputs, (list, tuple)):
            inputs = dict(zip(self._feed_names, inputs))
        deadline = deadline_s if deadline_s is not None \
            else self._deadline_s
        self._bump("requests")

        def bounded(what, **impl_kw):
            # the slot is released by the WORK when it finishes — on a
            # deadline miss the orphaned worker keeps it until then
            release = self._acquire_slot()

            def body():
                try:
                    return self._run_impl(inputs, **impl_kw)
                finally:
                    release()
            return resilience.run_with_deadline(body, deadline, what=what)

        try:
            return bounded("serving request")
        except resilience.DeadlineExceededError:
            self._bump("deadline_misses")
            if not degraded_ok or not self._meta["dynamic_batch"]:
                raise
            n = self._request_batch(inputs)
            natural = self._bucket(n)
            fb = self._warm_fallback_bucket(n)
            if natural in self._warm or fb is None:
                raise   # the slot itself is slow, not a cold compile
            resilience.record_event("degraded", batch=n,
                                    cold_bucket=natural, warm_bucket=fb)
            try:
                out = bounded("degraded serving request", force_bucket=fb)
            except resilience.DeadlineExceededError:
                self._bump("deadline_misses")
                raise
            except Exception:
                # the outer except Exception never sees failures raised
                # INSIDE this handler — count them here or health()
                # undercounts degraded-path hard errors
                self._bump("errors")
                raise
            self._bump("degraded_serves")
            return out
        except resilience.ServerOverloadedError:
            raise                     # counted where the slot was denied
        except Exception:
            self._bump("errors")
            raise


def load_serving_artifact(dirname, max_in_flight=None, deadline_s=None):
    return ServingPredictor(dirname, max_in_flight=max_in_flight,
                            deadline_s=deadline_s)
